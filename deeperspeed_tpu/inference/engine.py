"""Serving engine: continuous batching over a paged KV cache.

`InferenceEngine` is the serving-side sibling of the training
`DeepSpeedEngine`: it wraps the same model families (GPT-NeoX / GPT-2 —
their blocks share ONE implementation, `gpt_neox._block_qkv` /
`_block_post_attn`, so the decode path cannot drift from training
numerics), is driven by the same JSON config machinery (the validated
``"inference"`` block, `runtime.config.parse_inference_block`), loads
weights params-only through the manifest-verified checkpoint loader
(`checkpoint.load_module_checkpoint` — CRC verification and the
committed-tag fallback included, Adam moments never deserialized), and
applies `module_inject.prepare_inference_params` so weights rest in the
serving compute dtype.

Execution model (docs/inference.md):

- **Prefill/decode split.** New requests run one bucketed prefill
  (whole prompt, causal attention, K/V written to their pages in
  whole-page scatters); in-flight requests run one decode step each
  (one token through the Pallas paged decode-attention kernel,
  `ops/pallas/decode_attention.py`).
- **Fixed compiled shapes.** Prefill compiles per (batch bucket, length
  bucket), decode per batch bucket — the scheduler
  (`inference.scheduler`) only ever emits those shapes, so after the
  ladder warms up XLA never recompiles (`compile_count()` pins this in
  tests and the `DS_BENCH_SERVE` row).
- **State.** The page pools are donated through every compiled call and
  rebound, so XLA updates them in place; everything else (params,
  rotary cache) is read-only.

Sampling is deterministic: temperature 0 (default) is argmax;
temperature > 0 draws from `jax.random.categorical` under a fixed
config seed folded with the step counter — the same request stream
always produces the same tokens.
"""

import math
import random
import time
import types
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..compat import shard_map
from ..models import gpt2 as gpt2_mod
from ..models import gpt_neox as neox
from ..module_inject.replace_module import prepare_inference_params
from ..ops.pallas.decode_attention import paged_decode_attention
from ..ops.pallas.flash_attention import NEG_INF
from ..parallel.mesh import MODEL_AXIS
from ..runtime.config import (DeepSpeedConfig, parse_inference_block,
                              parse_quantization_block)
from ..runtime.config_utils import (DeepSpeedConfigError, load_config_json)
from ..runtime.fault_injection import (FaultInjector, InjectedServingFault,
                                       SERVING_FAULT_KINDS)
from ..runtime.precision import resolve_kv_cache_dtype
from ..utils.kv_retry import backoff_delay
from ..utils.logging import logger
from .admission import (AdmissionController, DrainAborted, RequestFailed,
                        validate_priority)
from .handoff import (ACCEPTED, HandoffChannel, HandoffRejected,
                      check_geometry, encode_pages, write_pages)
from .kv_cache import (PagedKVCache, PrefixCache, QuantizedPages,
                       pages_for_tokens, quantize_kv)
from .metrics import (PREFIX_HIT_RATE, PREFIX_PAGES_SHARED,
                      PREFIX_SAVED_PREFILL_TOKENS, REQUEST_STATUS_FAMILIES,
                      SPEC_ACCEPTANCE_RATE, ServeRequestMetrics)
from .scheduler import (FINISHED, RUNNING, ContinuousBatchingScheduler,
                        Request)


def _pow2_ladder(lo, hi):
    """lo, 2·lo, 4·lo, ... capped at hi (hi appended if not reached)."""
    out, v = [], lo
    while v < hi:
        out.append(v)
        v *= 2
    out.append(hi)
    return sorted(set(out))


class _Family:
    """The model-family seams the serving loop needs: token embedding,
    position stream, LM head. Everything between (the block body) is
    the shared `gpt_neox._block_qkv`/`_block_post_attn`."""

    def __init__(self, model, max_seq_len):
        self.cfg = model.config
        if isinstance(model, neox.GPTNeoX):
            self.kind = "gpt_neox"
            self._cos, self._sin, self.rot_dim = neox._rotary_cache(
                self.cfg, max_seq_len)
        elif isinstance(model, gpt2_mod.GPT2):
            self.kind = "gpt2"
            self._cos = jnp.zeros((max_seq_len, 0), jnp.float32)
            self._sin = jnp.zeros((max_seq_len, 0), jnp.float32)
            self.rot_dim = 0
        else:
            raise DeepSpeedConfigError(
                f"InferenceEngine serves the GPT-NeoX / GPT-2 families; "
                f"got {type(model).__name__}")

    def embed_prefill(self, params, tokens):
        """tokens [B, S] → [B, S, H] at absolute positions 0..S-1."""
        x = params["embed"]["wte"][tokens]
        if self.kind == "gpt2":
            x = x + params["embed"]["wpe"][:tokens.shape[1]][None]
        return x

    def embed_decode(self, params, tokens, positions):
        """tokens [B] at absolute `positions` [B] → [B, 1, H]."""
        x = params["embed"]["wte"][tokens][:, None, :]
        if self.kind == "gpt2":
            x = x + params["embed"]["wpe"][positions][:, None, :]
        return x

    def embed_at(self, params, tokens, positions):
        """tokens [B, S] at per-token absolute `positions` [B, S] →
        [B, S, H] (the chunk programs: a window starting mid-sequence)."""
        x = params["embed"]["wte"][tokens]
        if self.kind == "gpt2":
            x = x + params["embed"]["wpe"][positions]
        return x

    def cos_sin_prefill(self, seqlen):
        return (self._cos[:seqlen], self._sin[:seqlen], self.rot_dim)

    def cos_sin_decode(self, positions):
        """Per-batch rotary rows at `positions` [B] → ([B, 1, rot], ...)."""
        return (self._cos[positions][:, None, :],
                self._sin[positions][:, None, :], self.rot_dim)

    def cos_sin_at(self, positions):
        """Per-token rotary rows at `positions` [B, S] →
        ([B, S, rot], ...) — `apply_rotary` takes the 3-D form."""
        return (self._cos[positions], self._sin[positions], self.rot_dim)

    def head(self, params, h):
        """Final-norm hidden [B, H] → logits [B, V] (fp32)."""
        if self.kind == "gpt2":
            wte = params["embed"]["wte"]
        else:
            wte = params.get("embed_out", params["embed"])["wte"]
        return jnp.einsum("bh,vh->bv", h, wte.astype(h.dtype),
                          preferred_element_type=jnp.float32)

    def head_all(self, params, h):
        """Final-norm hidden [B, S, H] → logits [B, S, V] (fp32) —
        the speculative verify needs every window position's logits."""
        if self.kind == "gpt2":
            wte = params["embed"]["wte"]
        else:
            wte = params.get("embed_out", params["embed"])["wte"]
        return jnp.einsum("bsh,vh->bsv", h, wte.astype(h.dtype),
                          preferred_element_type=jnp.float32)


class InferenceEngine:
    """Continuous-batching serving over the paged KV cache.

    ``model`` is a `models.gpt_neox.GPTNeoX` or `models.gpt2.GPT2`
    wrapper; ``config`` a dict / JSON path / `DeepSpeedConfig` holding
    the validated ``"inference"`` block; ``params`` an optional natural
    parameter pytree (else `load_checkpoint` or `model.init_params`).
    """

    def __init__(self, model, config=None, config_params=None, params=None,
                 mesh=None, rng=None, monitor=None, draft_model=None,
                 draft_params=None, owns_monitor=True,
                 handoff_transport=None):
        self.model = model
        cfg = model.config
        if getattr(cfg, "moe_num_experts", 0):
            raise DeepSpeedConfigError(
                "serving MoE models is not supported yet: the decode "
                "block would silently drop the expert routing")
        if getattr(cfg, "attention_engine", "dense") != "dense":
            raise DeepSpeedConfigError(
                "serving needs attention_engine='dense' (the block-"
                "sparse engine has no decode variant)")
        if getattr(model, "_attn_fn", None) is not None:
            raise DeepSpeedConfigError(
                "serving a sequence-parallel model is not supported "
                "(decode is one token; there is no sequence to shard)")

        # -- config --------------------------------------------------------
        raw = config_params if config_params is not None else config
        if isinstance(raw, DeepSpeedConfig):
            self.inference_params = raw.inference_params
            telemetry_config = raw.telemetry_config
            quantization = raw.quantization_config
        else:
            if raw is None:
                raise DeepSpeedConfigError(
                    "InferenceEngine requires a config with an "
                    "'inference' block")
            d = raw if isinstance(raw, dict) else load_config_json(raw)
            self.inference_params = parse_inference_block(d)
            quantization = parse_quantization_block(d) or None
            # reuse the training parser's telemetry validation without
            # dragging in the batch triad it also wants
            ns = types.SimpleNamespace()
            DeepSpeedConfig._parse_telemetry_block(ns, d)
            telemetry_config = ns.telemetry_config
        if not self.inference_params:
            raise DeepSpeedConfigError(
                "the 'inference' config block is required (with "
                "\"enabled\": true) to build an InferenceEngine")
        ip = self.inference_params

        self.page_size = ip["page_size"]
        self.max_seq_len = ip["max_seq_len"] or cfg.max_seq_len
        if self.max_seq_len > cfg.max_seq_len:
            raise DeepSpeedConfigError(
                f"inference.max_seq_len {self.max_seq_len} exceeds the "
                f"model's max_seq_len {cfg.max_seq_len}")
        if self.max_seq_len % self.page_size:
            raise DeepSpeedConfigError(
                f"the serving window max_seq_len {self.max_seq_len} must "
                f"be a multiple of page_size {self.page_size} (the paged "
                f"re-prefill ladder cannot cover a misaligned tail); set "
                f"inference.max_seq_len explicitly")
        if ip["num_pages"] - 1 < pages_for_tokens(self.max_seq_len,
                                                  self.page_size):
            raise DeepSpeedConfigError(
                f"inference.num_pages {ip['num_pages']} cannot hold even "
                f"one max_seq_len sequence "
                f"({pages_for_tokens(self.max_seq_len, self.page_size)} "
                f"pages + the reserved trash page)")
        self.max_batch_size = ip["max_batch_size"]
        self.temperature = ip["temperature"]
        self.seed = ip["seed"]
        self._attn_backend = (None if ip["kernel"] == "auto"
                              else ip["kernel"])

        if ip["prefill_lengths"]:
            bad = [b for b in ip["prefill_lengths"] if b > self.max_seq_len]
            if bad:
                raise DeepSpeedConfigError(
                    f"inference.prefill_lengths {bad} exceed the serving "
                    f"window max_seq_len {self.max_seq_len}")
            self.prefill_lengths = ip["prefill_lengths"]
        else:
            self.prefill_lengths = _pow2_ladder(self.page_size,
                                                self.max_seq_len)
        self.prefill_batch_sizes = ip["prefill_batch_sizes"] or \
            [b for b in (1, 2, 4) if b <= self.max_batch_size]
        self.decode_batch_sizes = ip["decode_batch_sizes"] or \
            _pow2_ladder(1, self.max_batch_size)

        # -- mesh / params -------------------------------------------------
        self.mesh = mesh
        self.mp = 1
        if mesh is not None and MODEL_AXIS in mesh.axis_names:
            self.mp = int(mesh.shape[MODEL_AXIS])
        if params is None:
            params = model.init_params(
                rng if rng is not None else jax.random.PRNGKey(0))
        # compute dtype comes from a matmul WEIGHT: 1-D leaves (biases,
        # norms) deliberately rest in fp32 (`prepare_inference_params`),
        # so the first leaf would read fp32 off a bf16 model and
        # silently double weight HBM
        leaves = jax.tree_util.tree_leaves(params)
        self.compute_dtype = next(
            (leaf.dtype for leaf in leaves
             if getattr(leaf, "ndim", 0) >= 2), leaves[0].dtype)
        # kv_cache_dtype overrides the CACHE pools only (K/V are cast —
        # or int8-quantized with per-page scales — on write, attention
        # runs at pool dtype) — it never re-casts the weights
        kv_dtype = ip["kv_cache_dtype"]
        self.kv_cache_dtype = (resolve_kv_cache_dtype(kv_dtype)
                               if kv_dtype else self.compute_dtype)
        self.kv_quant = self.kv_cache_dtype == jnp.int8
        # the validated "quantization" block (weights choice): int8
        # block matmul weights at rest (docs/quantization.md)
        self.weight_quant = (quantization or {}).get("weights")
        if self.weight_quant and self.mp > 1:
            raise DeepSpeedConfigError(
                "quantization.weights with a model-parallel mesh is "
                "unsupported: the per-channel scale leaves have no "
                "tensor-parallel placement yet — serve quantized "
                "weights on a replicated (mp=1) mesh")
        # structure template for params-only checkpoint loads: the
        # QUANTIZED tree splits each weight into (qval, scale) leaves,
        # but checkpoints store the natural layout — keep an abstract
        # natural-structure template (shapes only, nothing resident)
        self._natural_like = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                           jnp.result_type(l)), params)
        params = prepare_inference_params(params, self.compute_dtype,
                                          weight_quant=self.weight_quant)
        self._set_params(params)

        # -- cache / scheduler ---------------------------------------------
        self.family = _Family(model, self.max_seq_len)
        self.cache = PagedKVCache(
            num_layers=cfg.num_layers, num_pages=ip["num_pages"],
            num_heads=cfg.num_heads, page_size=self.page_size,
            head_dim=cfg.head_dim, dtype=self.kv_cache_dtype, mesh=mesh)
        # -- prefix/radix cache + speculative decoding (both default-off:
        #    without their config sub-blocks the engine is bit-identical
        #    to the plain PR 8 serving loop) --------------------------------
        self.prefix_cache = None
        if ip["prefix_cache"] is not None:
            if self.mp > 1:
                raise DeepSpeedConfigError(
                    "inference.prefix_cache with a model-parallel mesh is "
                    "unsupported: the chunk-prefill attention gathers the "
                    "head-sharded pools without a shard_map yet — serve "
                    "the prefix cache on a replicated (mp=1) mesh")
            self.prefix_cache = PrefixCache(
                self.cache, max_pages=ip["prefix_cache"]["max_pages"])
        self.spec_k = 0
        self.draft_model = None
        self.draft_cache = None
        if ip["speculative"] is not None:
            sp = ip["speculative"]
            if draft_model is None:
                raise DeepSpeedConfigError(
                    "inference.speculative is enabled but no draft_model "
                    "was passed to InferenceEngine (the draft proposes "
                    "the tokens the target verifies)")
            if self.mp > 1:
                raise DeepSpeedConfigError(
                    "inference.speculative with a model-parallel mesh is "
                    "unsupported: the draft pools and the verify chunk "
                    "have no tensor-parallel placement yet — serve "
                    "speculation on a replicated (mp=1) mesh")
            dcfg = draft_model.config
            if getattr(dcfg, "moe_num_experts", 0):
                raise DeepSpeedConfigError(
                    "an MoE draft model is not supported (the decode "
                    "block would silently drop the expert routing)")
            if dcfg.vocab_size != cfg.vocab_size:
                raise DeepSpeedConfigError(
                    f"draft vocab_size {dcfg.vocab_size} != target "
                    f"vocab_size {cfg.vocab_size}: draft proposals would "
                    f"index a different token space")
            if dcfg.max_seq_len < self.max_seq_len:
                raise DeepSpeedConfigError(
                    f"draft max_seq_len {dcfg.max_seq_len} is smaller "
                    f"than the serving window {self.max_seq_len}: the "
                    f"draft could not reach every decode position")
            self.spec_k = sp["num_draft_tokens"]
            self.draft_model = draft_model
            if draft_params is None:
                draft_params = draft_model.init_params(
                    jax.random.PRNGKey(self.seed))
            self.draft_params = prepare_inference_params(
                draft_params, self.compute_dtype,
                weight_quant=sp["draft_weight_quant"])
            self.draft_stacked = self._stacked_blocks(self.draft_params)
            self.draft_family = _Family(draft_model, self.max_seq_len)
            # the draft's shadow pools MIRROR the target allocator: same
            # num_pages/page_size, so one page id addresses a sequence's
            # K/V in both models and no second allocator exists — every
            # write path (prefill twin, chunk twin, propose) lands draft
            # K/V at the page ids the target's scheduler handed out
            self.draft_cache = PagedKVCache(
                num_layers=dcfg.num_layers, num_pages=ip["num_pages"],
                num_heads=dcfg.num_heads, page_size=self.page_size,
                head_dim=dcfg.head_dim, dtype=self.kv_cache_dtype)
            # host-side rejection sampling (temperature > 0): its own
            # deterministic stream, separate from the jax sampling keys
            self._spec_rng = np.random.default_rng(self.seed)

        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_seq_len=self.max_seq_len,
            token_budget=ip["token_budget"],
            max_batch_size=self.max_batch_size,
            prefill_lengths=self.prefill_lengths,
            prefill_batch_sizes=self.prefill_batch_sizes,
            decode_batch_sizes=self.decode_batch_sizes,
            prefix_cache=self.prefix_cache, spec_tokens=self.spec_k)
        self.n_pages_max = pages_for_tokens(self.max_seq_len,
                                            self.page_size)
        # precision identity of this serving engine: the bench serve row
        # records it in `extra` so BENCH history can attribute serving
        # deltas to precision changes (docs/quantization.md)
        self.dtypes = {
            "weight": self.weight_quant or
            str(jnp.dtype(self.compute_dtype)),
            "compute": str(jnp.dtype(self.compute_dtype)),
            "kv_cache": ("int8" if self.kv_quant
                         else str(jnp.dtype(self.kv_cache_dtype))),
        }

        # -- telemetry (spans: schedule / prefill / decode; admission
        #    wait is a per-request scalar — docs/inference.md) ------------
        from ..runtime.telemetry import build_telemetry
        self.monitor = monitor
        # co-residency contract (docs/rl.md): when the monitor is BORROWED
        # from a co-located training engine (owns_monitor=False), drain()
        # flushes it but must not close it — the training engine still
        # records Train/* scalars, and TensorBoardMonitor registers its
        # own weak atexit close, so no second registration happens here
        self._owns_monitor = bool(owns_monitor)
        self.telemetry = build_telemetry(telemetry_config, monitor=monitor,
                                         devices=jax.local_devices())

        self._compiled = {}
        self._steps = 0
        self.stats = {"steps": 0, "prefill_requests": 0,
                      "prefill_tokens": 0, "decode_tokens": 0,
                      "evictions": 0, "finished": 0,
                      "schedule_s": 0.0, "prefill_s": 0.0,
                      "decode_s": 0.0, "admission_wait_s": 0.0,
                      "queue_depth": 0.0, "page_pool_util": 0.0,
                      # terminal-status taxonomy: every request reaches
                      # exactly one (docs/inference.md)
                      "requests_ok": 0, "requests_shed": 0,
                      "requests_deadline_exceeded": 0,
                      "requests_failed": 0,
                      "quarantines": 0, "retries": 0,
                      # speculative decoding: proposed/accepted draft
                      # tokens and verify steps (0 when speculation off)
                      "spec_steps": 0, "spec_proposed": 0,
                      "spec_accepted": 0,
                      # disaggregated prefill/decode handoff (all zero
                      # on a unified engine): prefill-side offers
                      # (sent/acked/rejected/expired) and decode-side
                      # verdicts (installed/refused)
                      "handoff_sent": 0, "handoff_acked": 0,
                      "handoff_rejected": 0, "handoff_expired": 0,
                      "handoff_installed": 0, "handoff_refused": 0}
        # request-level latency histograms (inference/metrics.py):
        # admission-wait / TTFT / inter-token distributions, fanned out
        # to the monitor's export backends (Prometheus histogram
        # families) at observation time
        self.request_metrics = ServeRequestMetrics(monitor=monitor)

        # graceful drain (SIGTERM): flag-only handler, acted on at the
        # next serving-loop iteration — the PR 3 signal discipline
        self.drain_deadline_s = ip["drain_deadline_s"]
        self._drain_requested = False
        self._drain_signum = None
        self._prev_handlers = {}

        # -- robustness layer (docs/inference.md "Serving under
        #    failure"): admission control, retry/poison policy, hang
        #    watchdog, serving fault injection -------------------------
        self.default_priority = ip["default_priority"]
        self.retry_params = ip["retry"]
        self._retry_rng = random.Random(ip["seed"])
        self.admission = (AdmissionController(ip["admission"])
                          if ip["admission"] else None)
        self.fault_injector = FaultInjector.from_config_env(
            config_spec=ip["fault_injection"])
        self._step_faults = []      # serving faults fired this step
        self._pressure_pages = []   # page_pool_pressure seizures
        self.watchdog = None
        self.watchdog_fires = 0
        self.last_stack_dump = None
        if ip["hang_timeout_s"] > 0:
            from ..runtime.sentinel import HangWatchdog
            self.watchdog = HangWatchdog(ip["hang_timeout_s"], self,
                                         "_on_serving_hang")

        # -- disaggregated prefill/decode (docs/inference.md
        #    "Disaggregated serving"): role, pool identity, and the
        #    cross-pool KV-page handoff channel ---------------------------
        dg = ip["disaggregation"]
        self.role = dg["role"]
        self.pool_id = dg["pool_id"]
        self.handoff_timeout_s = dg["handoff_timeout_s"]
        # the validated inference.router weights (None when absent) —
        # a ServeRouter fronting this pool picks them up from here
        self.router_params = ip["router"]
        self.handoff = None
        self._handoff_outbox = []      # prefilled requests awaiting offer
        self._pending_handoff = {}     # offer key -> (request, offered_at)
        self._handoff_draining = False
        if self.role != "unified":
            if handoff_transport is None:
                raise DeepSpeedConfigError(
                    f"inference.disaggregation.role={self.role!r} needs a "
                    f"handoff_transport (the coordination-service KV the "
                    f"pages travel over — elasticity.heartbeat."
                    f"InMemoryTransport / CoordinationTransport)")
            if self.mp > 1:
                raise DeepSpeedConfigError(
                    "disaggregated serving with a model-parallel mesh is "
                    "unsupported: the page payload has no tensor-parallel "
                    "placement yet — split pools on replicated (mp=1) "
                    "meshes")
            self.handoff = HandoffChannel(handoff_transport, self.pool_id)
            if self.role == "decode":
                # a decode pool never prefills FRESH requests: the drain
                # gate blocks queue admissions permanently, while evicted
                # / quarantined sequences (whose K/V must be rebuilt
                # locally) still re-admit through it
                self.scheduler.stop_admissions()
            # stamp the scrape: every Serve/* family this pool exports
            # carries its role + pool identity
            if monitor is not None:
                hook = getattr(monitor, "set_export_labels", None)
                if hook is not None:
                    hook({"role": self.role, "host": self.pool_id})

    # ------------------------------------------------------------------
    # weights
    # ------------------------------------------------------------------

    def _place_params(self, params):
        if self.mp > 1:
            specs = self.model.param_specs(params, self.mesh)
            return jax.tree_util.tree_map(
                lambda p, s: jax.device_put(
                    p, NamedSharding(self.mesh, s)), params, specs,
                is_leaf=lambda x: isinstance(x, P))
        return params

    def _set_params(self, params):
        """Place the params and pre-stack the block weights ONCE:
        decode is weight-bandwidth bound, and stacking inside the
        compiled step would materialize a full copy of the block
        params every call (params are runtime jit inputs — XLA cannot
        hoist the stack out)."""
        self.params = self._place_params(params)
        stacked = self._stacked_blocks(self.params)
        if self.mp > 1:
            specs = self.model.param_specs(self.params, self.mesh)
            stacked = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, P(None, *s))),
                stacked, specs["blocks"][0])
        self.params_stacked = stacked
        # a weight hot-swap invalidates every registered prefix page:
        # the cached K/V is a function of the OLD weights, so new
        # requests must not share it — drop the registry and detach
        # waiting attachments (running requests keep decoding on their
        # old-weights K/V, the pre-existing hot-swap semantics)
        pc = getattr(self, "prefix_cache", None)
        if pc is not None:
            pc.clear()
            self.scheduler.detach_waiting_prefixes()

    def load_checkpoint(self, load_dir, tag=None):
        """Params-only restore through the manifest-verified loader:
        CRC verification and the committed-tag fallback run exactly as
        in training resume, but only the module tree is deserialized —
        a serving restart never touches Adam moments."""
        from ..checkpoint.checkpointing import load_module_checkpoint
        path, natural, client_state = load_module_checkpoint(
            load_dir, tag=tag, like=self._natural_like)
        if path is None:
            return None, {}
        params = prepare_inference_params(natural, self.compute_dtype,
                                          weight_quant=self.weight_quant)
        # the compiled programs take params as runtime arguments, so the
        # warmed bucket executables stay valid across a weight hot-swap
        # (same avals = jit cache hit) — no recompile ladder to repay
        self._set_params(params)
        return path, client_state

    def hot_swap_weights(self, natural_params):
        """In-process train->serve weight flow (docs/rl.md): re-run
        `prepare_inference_params` (dtype cast + optional int8
        requantization — weights AND scales are runtime jit args) and
        swap via `_set_params`. The warmed bucket executables stay valid
        because every compiled program takes params as runtime
        arguments: same avals = jit cache hit, zero recompiles.

        Returns ``{"swap_ms", "compile_delta"}``; a non-zero
        compile_delta after warmup is the regression the satellite test
        pins to 0."""
        before = self.compile_count()
        t0 = time.perf_counter()
        params = prepare_inference_params(natural_params,
                                          self.compute_dtype,
                                          weight_quant=self.weight_quant)
        self._set_params(params)
        jax.block_until_ready(self.params)
        swap_ms = (time.perf_counter() - t0) * 1e3
        return {"swap_ms": swap_ms,
                "compile_delta": self.compile_count() - before}

    def sampler_state(self):
        """Deterministic-replay snapshot of every sampling stream: the
        fold_in step counter (`_next_rng`) and, when speculation is
        armed, the host-side rejection-sampling PCG64 state. Pure data —
        checkpointable via client_state."""
        state = {"steps": int(self._steps)}
        if self.spec_k:
            state["spec_rng"] = self._spec_rng.bit_generator.state
        return state

    def restore_sampler_state(self, state):
        """Restore `sampler_state()`; sampling is a pure function of
        (seed, steps), so a restored engine reproduces the exact token
        stream an uninterrupted run would have drawn."""
        self._steps = int(state["steps"])
        if self.spec_k and "spec_rng" in state:
            self._spec_rng.bit_generator.state = state["spec_rng"]

    # ------------------------------------------------------------------
    # compiled programs (one per bucket — the no-recompile discipline)
    # ------------------------------------------------------------------

    def compile_count(self):
        """Total compiled executables across all bucketed programs; the
        zero-recompile tests/bench pin that this stops growing once the
        bucket ladder has warmed up."""
        total = 0
        for fn in self._compiled.values():
            total += (fn._cache_size() if hasattr(fn, "_cache_size")
                      else 1)
        return total

    def _sample(self, logits, rng):
        if self.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            rng, logits / self.temperature, axis=-1).astype(jnp.int32)

    def _attention(self, q, k_pages, v_pages, page_table, lengths):
        """Paged decode attention, shard_mapped over the model axis when
        the mesh shards heads (attention is head-independent, so each
        shard runs the kernel on its local heads — no collective).
        Int8 pools arrive as `QuantizedPages`; the per-page scale pools
        ride the same head-sharded placement as the data pools."""
        scales = {}
        if isinstance(k_pages, QuantizedPages):
            scales = {"k_scales": k_pages.scale, "v_scales": v_pages.scale}
            k_pages, v_pages = k_pages.data, v_pages.data
        if self.mp > 1:
            def mapped(q, k, v, pt, ln, *sc):
                kw = ({"k_scales": sc[0], "v_scales": sc[1]} if sc
                      else {})
                return paged_decode_attention(
                    q, k, v, pt, ln, backend=self._attn_backend, **kw)

            pool_spec = P(None, MODEL_AXIS, None, None)
            scale_specs = ((P(None, MODEL_AXIS, None),) * 2 if scales
                           else ())
            f = shard_map(
                mapped, self.mesh,
                in_specs=(P(None, MODEL_AXIS, None), pool_spec,
                          pool_spec, P(None, None), P(None)) + scale_specs,
                out_specs=P(None, MODEL_AXIS, None),
                check_vma=False)
            return f(q, k_pages, v_pages, page_table, lengths,
                     *scales.values())
        return paged_decode_attention(q, k_pages, v_pages, page_table,
                                      lengths, backend=self._attn_backend,
                                      **scales)

    @staticmethod
    def _stacked_blocks(params):
        return jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *params["blocks"])

    def _prefill_fn(self, batch, seqlen):
        key = ("prefill", batch, seqlen)
        if key in self._compiled:
            return self._compiled[key]
        cfg = self.model.config
        fam = self.family
        use_pallas = getattr(self.model, "use_pallas", True)
        ps = self.page_size
        n_pages_row = seqlen // ps
        cos_sin = fam.cos_sin_prefill(seqlen)

        def prefill(params, stacked, tokens, lengths, page_table, k_pool,
                    v_pool, rng):
            B, S = tokens.shape
            pos = jnp.arange(S, dtype=jnp.int32)[None, :]
            # 1 = real token, 0 = pad: the segmented attention kernels
            # (and the XLA fallback's segment mask) then give each row
            # causal attention over its own tokens only
            seg = (pos < lengths[:, None]).astype(jnp.int32)
            x = fam.embed_prefill(params, tokens)

            def body(carry, bp):
                y, kv = neox._block_core(
                    cfg, bp, carry, cos_sin, use_pallas, mp=1,
                    reduce_fn=lambda t: t, return_kv=True,
                    segment_ids=seg)
                return y, kv

            x, (ks, vs) = jax.lax.scan(body, x, stacked)

            # whole-page scatter: [B, S, H, D] → B·S/ps page tiles at
            # the page-table ids (pad rows hold table id 0 — the trash
            # page — so duplicates only ever collide there)
            flat_pt = page_table.reshape(-1)
            H, D = cfg.num_heads, cfg.head_dim

            def write(pool, new):
                tiles = new.reshape(B, n_pages_row, ps, H, D)
                tiles = jnp.moveaxis(tiles, 3, 2)
                tiles = tiles.reshape(B * n_pages_row, H, ps, D)
                if isinstance(pool, QuantizedPages):
                    # int8 pages: quantize each (head, slot) vector and
                    # scatter data + scale through the same page ids
                    q8, sc = quantize_kv(tiles)
                    return QuantizedPages(
                        pool.data.at[flat_pt].set(q8),
                        pool.scale.at[flat_pt].set(
                            sc.astype(pool.scale.dtype)))
                return pool.at[flat_pt].set(tiles.astype(pool.dtype))

            k_pool = jax.vmap(write)(k_pool, ks)
            v_pool = jax.vmap(write)(v_pool, vs)

            idx = jnp.clip(lengths - 1, 0, S - 1)
            h_last = x[jnp.arange(B), idx][:, None, :]
            h_last = neox.layer_norm(h_last, params["final_ln"]["scale"],
                                     params["final_ln"]["bias"],
                                     cfg.layernorm_eps)
            logits = fam.head(params, h_last[:, 0])
            return self._sample(logits, rng), k_pool, v_pool

        fn = jax.jit(prefill, donate_argnums=(5, 6))
        self._compiled[key] = fn
        return fn

    def _decode_fn(self, batch):
        key = ("decode", batch)
        if key in self._compiled:
            return self._compiled[key]
        cfg = self.model.config
        fam = self.family
        ps = self.page_size
        H, D = cfg.num_heads, cfg.head_dim

        def decode(params, stacked, tokens, lengths, page_table, k_pool,
                   v_pool, rng):
            B = tokens.shape[0]
            # lengths INCLUDE the token decoded this step; 0 marks an
            # inactive (padding) row whose page table is all trash
            pos = jnp.maximum(lengths - 1, 0)
            x = fam.embed_decode(params, tokens, pos)
            cos, sin, rot_dim = fam.cos_sin_decode(pos)
            page_idx = jnp.take_along_axis(
                page_table, (pos // ps)[:, None], axis=1)[:, 0]
            slot = pos % ps

            def store(pool, vec):
                """One decoded token's K or V row into its page slot;
                int8 pools quantize per (head) vector and land the
                scale in the page-aligned scale pool."""
                if isinstance(pool, QuantizedPages):
                    q8, sc = quantize_kv(vec)
                    return QuantizedPages(
                        pool.data.at[page_idx, :, slot].set(q8),
                        pool.scale.at[page_idx, :, slot].set(
                            sc.astype(pool.scale.dtype)))
                return pool.at[page_idx, :, slot].set(
                    vec.astype(pool.dtype))

            def body(carry, xs):
                bp, kp, vp = xs
                q, k, v = neox._block_qkv(cfg, bp, carry, cos, sin,
                                          rot_dim, H)
                kp = store(kp, k[:, 0])
                vp = store(vp, v[:, 0])
                qrow = q[:, 0] if isinstance(kp, QuantizedPages) \
                    else q[:, 0].astype(kp.dtype)
                attn = self._attention(qrow, kp, vp,
                                       page_table, lengths)
                attn = attn.astype(carry.dtype)
                out = neox._block_post_attn(
                    cfg, bp, carry, attn.reshape(B, 1, H * D),
                    reduce_fn=lambda t: t)
                return out, (kp, vp)

            x, (k_pool, v_pool) = jax.lax.scan(
                body, x, (stacked, k_pool, v_pool))
            h = neox.layer_norm(x, params["final_ln"]["scale"],
                                params["final_ln"]["bias"],
                                cfg.layernorm_eps)
            logits = fam.head(params, h[:, 0])
            return self._sample(logits, rng), k_pool, v_pool

        fn = jax.jit(decode, donate_argnums=(5, 6))
        self._compiled[key] = fn
        return fn

    def _chunk_fn(self, batch, seqlen, which, mode):
        """The mid-sequence window program: run `seqlen` tokens per row
        starting at per-row absolute positions (`start`, `n_new` valid),
        writing their K/V into the row's pages and attending over the
        WHOLE page table (earlier positions included — that is what
        makes it a continuation, not a fresh prefill). One program
        serves three duties, compiled per (model, duty, shape):

        - ``("target", "sample")`` — prefix-cache suffix prefill: the
          shared pages already hold the prefix K/V, the window covers
          only the suffix, and the first token samples at the last
          valid position;
        - ``("target", "verify")`` — speculative verify: the window is
          [last token, k proposals]; returns per-position argmax tokens
          (greedy) or fp32 next-token probs (sampled acceptance);
        - ``("draft", "write")`` — draft-pool twin of any prefill
          (full or suffix): writes draft K/V only, no head.
        """
        key = ("chunk", which, mode, batch, seqlen)
        if key in self._compiled:
            return self._compiled[key]
        model = self.model if which == "target" else self.draft_model
        fam = self.family if which == "target" else self.draft_family
        cfg = model.config
        ps = self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        NP = self.n_pages_max
        window = self.max_seq_len
        sm_scale = 1.0 / math.sqrt(D)

        def chunk(params, stacked, tokens, start, n_new, page_table,
                  k_pool, v_pool, rng):
            B, S = tokens.shape
            offs = jnp.arange(S, dtype=jnp.int32)[None, :]
            pos = start[:, None] + offs
            valid = offs < n_new[:, None]
            pos_c = jnp.clip(pos, 0, window - 1)
            x = fam.embed_at(params, tokens, pos_c)
            cos, sin, rot_dim = fam.cos_sin_at(pos_c)
            # invalid window slots write to the trash page (the padding
            # idiom everywhere else in this engine)
            page_idx = jnp.take_along_axis(page_table, pos_c // ps, axis=1)
            page_idx = jnp.where(valid, page_idx, 0)
            slot = pos_c % ps
            # per-query attention bound: position p sees cache slots
            # 0..p; invalid rows see nothing (safe-softmax zeros them)
            qpos = jnp.where(valid, pos_c, -1)

            def store(pool, new):
                """Window K/V rows [B, S, H, D] into their (page, slot)
                cells; int8 pools quantize per (head, token) vector —
                the same `quantize_kv` every other write path uses, so
                identical tokens produce identical page bytes."""
                if isinstance(pool, QuantizedPages):
                    q8, sc = quantize_kv(new)
                    return QuantizedPages(
                        pool.data.at[page_idx, :, slot].set(q8),
                        pool.scale.at[page_idx, :, slot].set(
                            sc.astype(pool.scale.dtype)))
                return pool.at[page_idx, :, slot].set(
                    new.astype(pool.dtype))

            def gather(pool):
                """Row-gathered cache [B, H, NP·ps, D] (the XLA decode
                fallback's layout; int8 dequantizes at the gather)."""
                if isinstance(pool, QuantizedPages):
                    d = pool.data[page_table].astype(jnp.float32) * \
                        pool.scale[page_table].astype(jnp.float32)[..., None]
                else:
                    d = pool[page_table]
                return jnp.moveaxis(d, 2, 1).reshape(B, H, NP * ps, D)

            def attend(q, kp, vp):
                k = gather(kp)
                v = gather(vp)
                q = jnp.moveaxis(q, 2, 1)              # [B, H, S, D]
                q = (q.astype(jnp.float32)
                     if isinstance(kp, QuantizedPages)
                     else q.astype(k.dtype))
                s = jnp.einsum("bhsd,bhkd->bhsk", q, k,
                               preferred_element_type=jnp.float32)
                s = s * sm_scale
                kpos = jnp.arange(NP * ps, dtype=jnp.int32)
                mask = kpos[None, None, None, :] <= qpos[:, None, :, None]
                s = jnp.where(mask, s, NEG_INF)
                m = jnp.max(s, axis=-1, keepdims=True)
                prob = jnp.exp(s - m)
                prob = jnp.where(s <= NEG_INF * 0.5, 0.0, prob)
                l = jnp.sum(prob, axis=-1, keepdims=True)
                l = jnp.where(l == 0.0, 1.0, l)
                out = jnp.einsum("bhsk,bhkd->bhsd",
                                 (prob / l).astype(v.dtype), v,
                                 preferred_element_type=jnp.float32)
                return jnp.moveaxis(out, 1, 2).reshape(B, S, H * D)

            def body(carry, xs):
                bp, kp, vp = xs
                q, k, v = neox._block_qkv(cfg, bp, carry, cos, sin,
                                          rot_dim, H)
                # write BEFORE attending: every window key is visible,
                # causal masking (qpos) keeps attention autoregressive
                kp = store(kp, k)
                vp = store(vp, v)
                attn = attend(q, kp, vp).astype(carry.dtype)
                out = neox._block_post_attn(cfg, bp, carry, attn,
                                            reduce_fn=lambda t: t)
                return out, (kp, vp)

            x, (k_pool, v_pool) = jax.lax.scan(
                body, x, (stacked, k_pool, v_pool))
            if mode == "write":
                return k_pool, v_pool
            if mode == "sample":
                idx = jnp.clip(n_new - 1, 0, S - 1)
                h_last = x[jnp.arange(B), idx][:, None, :]
                h_last = neox.layer_norm(
                    h_last, params["final_ln"]["scale"],
                    params["final_ln"]["bias"], cfg.layernorm_eps)
                logits = fam.head(params, h_last[:, 0])
                return self._sample(logits, rng), k_pool, v_pool
            # mode == "verify": every position's next-token view
            h = neox.layer_norm(x, params["final_ln"]["scale"],
                                params["final_ln"]["bias"],
                                cfg.layernorm_eps)
            logits = fam.head_all(params, h)
            if self.temperature <= 0.0:
                out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                out = jax.nn.softmax(logits / self.temperature, axis=-1)
            return out, k_pool, v_pool

        fn = jax.jit(chunk, donate_argnums=(6, 7))
        self._compiled[key] = fn
        return fn

    def _propose_fn(self, batch):
        """The draft program: k+1 unrolled decode steps through the
        draft model against the draft pools (same page ids as the
        target's). Steps 0..k-1 argmax-propose the next token; the
        final step writes the last proposal's K/V without sampling, so
        the draft cache always covers every token the target may
        accept. Per-row `windows` gate writes (and attention) past a
        row's speculative window to the trash page — a row at its
        max_new_tokens edge (window 0) still gets its pending token's
        draft K/V written and nothing else."""
        key = ("spec_propose", batch)
        if key in self._compiled:
            return self._compiled[key]
        cfg = self.draft_model.config
        fam = self.draft_family
        ps = self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        k_steps = self.spec_k
        window = self.max_seq_len

        def propose(params, stacked, tokens, lengths, windows, page_table,
                    k_pool, v_pool):
            B = tokens.shape[0]
            base = jnp.maximum(lengths - 1, 0)
            proposed = []
            tok = tokens
            for j in range(k_steps + 1):
                pos = jnp.clip(base + j, 0, window - 1)
                active = (j <= windows) & (lengths > 0)
                x = fam.embed_decode(params, tok, pos)
                cos, sin, rot_dim = fam.cos_sin_decode(pos)
                page_idx = jnp.take_along_axis(
                    page_table, (pos // ps)[:, None], axis=1)[:, 0]
                page_idx = jnp.where(active, page_idx, 0)
                slot = pos % ps
                att_len = jnp.where(active, pos + 1, 0)

                def store(pool, vec, page_idx=page_idx, slot=slot):
                    if isinstance(pool, QuantizedPages):
                        q8, sc = quantize_kv(vec)
                        return QuantizedPages(
                            pool.data.at[page_idx, :, slot].set(q8),
                            pool.scale.at[page_idx, :, slot].set(
                                sc.astype(pool.scale.dtype)))
                    return pool.at[page_idx, :, slot].set(
                        vec.astype(pool.dtype))

                def body(carry, xs, cos=cos, sin=sin, rot_dim=rot_dim,
                         store=store, att_len=att_len):
                    bp, kp, vp = xs
                    q, k, v = neox._block_qkv(cfg, bp, carry, cos, sin,
                                              rot_dim, H)
                    kp = store(kp, k[:, 0])
                    vp = store(vp, v[:, 0])
                    qrow = q[:, 0] if isinstance(kp, QuantizedPages) \
                        else q[:, 0].astype(kp.dtype)
                    attn = self._attention(qrow, kp, vp, page_table,
                                           att_len)
                    attn = attn.astype(carry.dtype)
                    out = neox._block_post_attn(
                        cfg, bp, carry, attn.reshape(B, 1, H * D),
                        reduce_fn=lambda t: t)
                    return out, (kp, vp)

                x, (k_pool, v_pool) = jax.lax.scan(
                    body, x, (stacked, k_pool, v_pool))
                if j < k_steps:
                    h = neox.layer_norm(x, params["final_ln"]["scale"],
                                        params["final_ln"]["bias"],
                                        cfg.layernorm_eps)
                    logits = fam.head(params, h[:, 0])
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    proposed.append(tok)
            return jnp.stack(proposed, axis=1), k_pool, v_pool

        fn = jax.jit(propose, donate_argnums=(6, 7))
        self._compiled[key] = fn
        return fn

    # ------------------------------------------------------------------
    # the serving loop
    # ------------------------------------------------------------------

    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               request_id=None, priority=None, deadline_ms=None,
               ttft_slo_ms=None):
        """Enqueue one request; returns its id.

        ``priority`` is a class name (``interactive``/``batch``;
        config ``inference.default_priority`` when omitted) — typos
        raise with the choices listed. ``deadline_ms`` bounds the
        request's total wall clock (expired requests terminate with a
        typed `DeadlineExceeded`); ``ttft_slo_ms`` is its
        time-to-first-token objective (admission sheds the request when
        the measured TTFT EMA already exceeds it).

        Under overload the admission controller raises a typed
        `RequestRejected` (terminal status ``shed``) carrying a
        retry-after hint from the measured drain rate — the request
        never enters the queue."""
        if self.role == "decode":
            raise RuntimeError(
                f"decode-role pool {self.pool_id!r} does not accept "
                f"fresh requests — submit to a prefill pool (or the "
                f"front-end ServeRouter); its work arrives as KV-page "
                f"handoffs")
        priority = self.default_priority if priority is None else priority
        validate_priority(priority)
        for name, value in (("deadline_ms", deadline_ms),
                            ("ttft_slo_ms", ttft_slo_ms)):
            if value is not None and (
                    not isinstance(value, (int, float)) or
                    isinstance(value, bool) or value <= 0):
                raise ValueError(
                    f"{name} must be a number > 0 (milliseconds), got "
                    f"{value!r}")
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id, request_id=request_id,
                      priority=priority,
                      deadline_ms=(None if deadline_ms is None
                                   else float(deadline_ms)),
                      ttft_slo_ms=(None if ttft_slo_ms is None
                                   else float(ttft_slo_ms)))
        if self.admission is not None:
            usable = max(self.cache.num_pages - 1, 1)
            try:
                self.admission.admit(
                    req, queue_depth=len(self.scheduler.waiting) +
                    len(self.scheduler.quarantined),
                    page_pool_util=1.0 - self.cache.num_free / usable)
            except Exception:
                self.stats["requests_shed"] += 1
                raise
        return self.scheduler.add_request(req, now=time.perf_counter())

    def _next_rng(self):
        self._steps += 1
        return jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                  self._steps)

    def step(self):
        """One scheduler step: admit + prefill new requests, decode one
        token for every in-flight sequence. Returns a summary dict.

        A prefill/decode exception QUARANTINES the implicated batch
        (evict, free pages, capped-jittered retry; poisoned after
        ``retry.max_attempts`` consecutive failures) instead of killing
        the server — `step()` only raises on scheduler-invariant
        violations. The hang watchdog (``inference.hang_timeout_s``) is
        armed around the dispatch once the step's programs are warm
        (an XLA compile is not a hang) and fed on exit — including when
        the step DIES rather than hangs."""
        self._plan_step_faults()
        self._apply_page_pressure()
        try:
            return self._step_inner()
        finally:
            if self.watchdog is not None:
                self.watchdog.feed()
            self._release_page_pressure()

    def _step_inner(self):
        now = time.perf_counter()
        if self.handoff is not None:
            # pool discovery rides every step: the prefill side's dst
            # pick and the router's gauges read the freshest announce
            self.handoff.announce(self.role, self._pool_load())
            if self.role == "decode":
                # install BEFORE schedule(): a page set acked this step
                # joins this step's decode batch
                self._install_handoffs(now)
            else:
                self._poll_handoff_acks(now)
        t0 = now
        finished_before = len(self.scheduler.finished)
        with self.telemetry.span("schedule"):
            plan = self.scheduler.schedule(now=now)
        self.stats["schedule_s"] += time.perf_counter() - t0
        self.stats["evictions"] += len(plan.evicted)
        if plan.empty and self.scheduler.quarantined:
            # nothing dispatchable until a quarantine backoff window
            # closes: sleep toward the earliest retry_at (capped so
            # run()/drain() stay responsive to drain requests and
            # deadlines) instead of busy-spinning step() at full CPU —
            # an uncapped spin would also flood the monitor and burn
            # scripted fault-injection step windows on idle serials
            wake = min((r.retry_at for r in self.scheduler.quarantined
                        if r.retry_at is not None), default=now)
            time.sleep(min(max(wake - time.perf_counter(), 0.0), 0.05))
        for req in plan.prefills:
            if req.admitted_at is not None and req.enqueued_at is not None:
                wait = req.admitted_at - req.enqueued_at
                self.stats["admission_wait_s"] += wait
                self.request_metrics.observe_admission_wait(wait)
        # per-step gauges: scheduler backlog + KV page-pool occupancy —
        # the two saturation signals an autoscaler watches (and the
        # admission controller sheds on)
        usable = max(self.cache.num_pages - 1, 1)
        self.stats["queue_depth"] = float(len(self.scheduler.waiting))
        self.stats["page_pool_util"] = 1.0 - self.cache.num_free / usable

        if self.watchdog is not None and self._programs_warm(plan):
            self.watchdog.arm()

        if plan.prefills:
            t0 = time.perf_counter()
            ok = True
            with self.telemetry.span("prefill"):
                try:
                    fault = self._fault_fired("prefill_error")
                    if fault is not None:
                        raise InjectedServingFault(
                            "injected prefill_error fault")
                    self._run_prefill(plan)
                except Exception as e:  # noqa: BLE001 - quarantine, don't die
                    ok = False
                    self._quarantine_batch(plan.prefills, e, "prefill")
            self.stats["prefill_s"] += time.perf_counter() - t0
            if ok:
                self.stats["prefill_requests"] += len(plan.prefills)
                # r.cached is the pre-sampling context length (complete_
                # prefill pins it before appending the first token) —
                # len(r.context) here would double-count that token once
                # decode accounting starts
                self.stats["prefill_tokens"] += \
                    sum(r.cached for r in plan.prefills)

        if self.role == "prefill":
            # a prefill pool never decodes: freshly prefilled sequences
            # (first token sampled, K/V resident) leave the scheduler
            # for the handoff outbox before the next schedule() can
            # plan a decode batch over them
            self._collect_handoffs()
            self._dispatch_handoffs(now)

        # a mid-execution prefill failure may have run cache-loss
        # recovery, evicting EVERY running sequence (their K/V is
        # gone): the planned decode batch would read trash pages and
        # append garbage tokens — skip it; the evicted requests
        # re-prefill on later steps
        decodes_intact = all(r.state == RUNNING for r in plan.decodes)
        produced = 0
        if plan.decodes and decodes_intact:
            stall = self._fault_fired("decode_stall")
            if stall is not None:
                time.sleep(stall["seconds"])   # drives the watchdog
            t0 = time.perf_counter()
            ok = True
            with self.telemetry.span("decode"):
                try:
                    fault = self._fault_fired("decode_error")
                    if fault is not None:
                        raise InjectedServingFault(
                            "injected decode_error fault")
                    if self.spec_k:
                        produced = self._run_speculative(plan)
                    else:
                        produced = self._run_decode(plan)
                except Exception as e:  # noqa: BLE001
                    ok = False
                    produced = 0
                    self._quarantine_batch(plan.decodes, e, "decode")
            self.stats["decode_s"] += time.perf_counter() - t0
            if ok:
                self.stats["decode_tokens"] += produced

        finished = len(self.scheduler.finished) - finished_before
        self.stats["finished"] += finished
        self.stats["steps"] += 1
        self._sync_status_counts()
        if self.admission is not None and finished:
            self.admission.note_finished(finished)
        self._record_request_spans(plan)
        if self.monitor is not None:
            # per-step saturation series keyed by total generated tokens
            # (the Serve/* convention); buffered — no per-step flush
            total = self.stats["prefill_tokens"] + \
                self.stats["decode_tokens"]
            scalars = {
                "Serve/queue_depth": self.stats["queue_depth"],
                "Serve/page_pool_util": self.stats["page_pool_util"],
                "Serve/running": float(len(self.scheduler.running))}
            # per-status terminal counters: exported through every
            # monitor backend (Prometheus gauges + JSONL events)
            for status, tag in REQUEST_STATUS_FAMILIES.items():
                scalars[tag] = float(self.stats[f"requests_{status}"])
            if self.prefix_cache is not None:
                pcs = self.prefix_cache.stats
                scalars[PREFIX_HIT_RATE] = \
                    pcs["hits"] / max(pcs["lookups"], 1)
                scalars[PREFIX_PAGES_SHARED] = float(pcs["pages_shared"])
                scalars[PREFIX_SAVED_PREFILL_TOKENS] = \
                    float(pcs["saved_prefill_tokens"])
            if self.spec_k:
                scalars[SPEC_ACCEPTANCE_RATE] = \
                    self.stats["spec_accepted"] / \
                    max(self.stats["spec_proposed"], 1)
            if self.role != "unified":
                for key in ("handoff_sent", "handoff_acked",
                            "handoff_rejected", "handoff_expired",
                            "handoff_installed", "handoff_refused"):
                    scalars[f"Serve/{key}"] = float(self.stats[key])
            self.monitor.record(total, scalars)
        return {"prefilled": len(plan.prefills), "decoded": produced,
                "evicted": len(plan.evicted), "finished": finished}

    def _sync_status_counts(self):
        """Mirror the scheduler's terminal-status tallies into the
        engine stats (``shed`` is engine-owned: shed requests never
        enter the scheduler)."""
        sc = self.scheduler.status_counts
        self.stats["requests_ok"] = sc["ok"]
        self.stats["requests_deadline_exceeded"] = sc["deadline_exceeded"]
        self.stats["requests_failed"] = sc["failed"]

    # ------------------------------------------------------------------
    # step-failure quarantine + serving fault injection
    # ------------------------------------------------------------------

    def _plan_step_faults(self):
        """One injector turn per serving step: pop the serving-kind
        host faults fired for this step (training kinds in a shared
        DS_FAULT_INJECT plan are ignored here)."""
        self._step_faults = []
        if self.fault_injector is None:
            return
        self.fault_injector.plan_next_step()
        self._step_faults = [
            f for f in self.fault_injector.take_host_faults()
            if f["kind"] in SERVING_FAULT_KINDS]

    def _fault_fired(self, kind):
        return next((f for f in self._step_faults if f["kind"] == kind),
                    None)

    def _apply_page_pressure(self):
        """``page_pool_pressure`` fault: seize a fraction of the FREE
        pool for this step so scheduling runs under memory pressure
        (eviction, admission shedding); released at step end."""
        fault = self._fault_fired("page_pool_pressure")
        if fault is None:
            return
        n = int(self.cache.num_free * fault["factor"])
        got = self.cache.allocate(n)
        if got:
            self._pressure_pages.extend(got)
            logger.warning(
                f"fault injection: page_pool_pressure seized {len(got)} "
                f"free page(s) for this step")

    def _release_page_pressure(self):
        if self._pressure_pages:
            self.cache.free(self._pressure_pages)
            self._pressure_pages = []

    def _quarantine_batch(self, requests, exc, phase):
        """A prefill/decode step failed: quarantine every implicated
        request (attribution is batch-granular — the failing request
        cannot be identified inside one compiled call; innocent
        co-batched requests reset their failure run at their next
        completed step). Transient failures get capped-jittered
        retries; a request failing ``retry.max_attempts`` consecutive
        steps is poisoned permanently with a typed `RequestFailed`
        (the serving mirror of PR 9's poison-step detector)."""
        now = time.perf_counter()
        self._recover_cache_if_lost(now)
        # the exception rides on poisoned requests (RequestFailed.
        # last_error) that live until the caller pops them: drop its
        # traceback NOW, or the stored frame graph pins this step's
        # plan/batch arrays (and the engine) for that whole lifetime
        exc.__traceback__ = None
        rp = self.retry_params
        poisoned = 0
        for req in requests:
            if req.state == FINISHED:
                continue       # cache-loss recovery may have failed it
            req.failures += 1
            if req.failures >= rp["max_attempts"]:
                poisoned += 1
                self.scheduler.finish_failed(req, RequestFailed(
                    f"request {req.request_id} failed {req.failures} "
                    f"consecutive {phase} steps — poisoned "
                    f"({type(exc).__name__}: {exc})",
                    last_error=exc, attempts=req.failures))
            else:
                delay_ms = backoff_delay(
                    req.failures, rp["backoff_base_ms"],
                    rp["backoff_cap_ms"], rp["jitter"], self._retry_rng)
                self.scheduler.quarantine_request(
                    req, retry_at=now + delay_ms / 1e3, now=now)
                self.stats["retries"] += 1
        self.stats["quarantines"] += 1
        logger.warning(
            f"serving {phase} step failed ({type(exc).__name__}: {exc}) "
            f"— quarantined {len(requests)} request(s) "
            f"({poisoned} poisoned); the server stays up")

    def _recover_cache_if_lost(self, now):
        """A compiled call that died MID-EXECUTION consumed the donated
        K/V pools: rebuild them zeroed and evict every running sequence
        (their cached context is gone — eviction re-prefills it from
        the full token history on readmission). Errors raised before
        dispatch (the common case, incl. injected faults) leave the
        donated buffers intact and skip this entirely."""
        k_data = self.cache.data_array(self.cache.k)
        deleted = getattr(k_data, "is_deleted", lambda: False)()
        if not deleted:
            return
        logger.error(
            "serving step died mid-execution with the KV pools donated "
            "— rebuilding zeroed pools and re-prefilling every running "
            "sequence")
        self.cache.reset_pools()
        if self.draft_cache is not None:
            # the draft pools ride the same compiled calls (donated):
            # assume them consumed too and rebuild — the re-prefills
            # rewrite both models' K/V from the full token history
            self.draft_cache.reset_pools()
        if self.prefix_cache is not None:
            # registered prefix K/V died with the pools: drop every
            # chain and detach not-yet-admitted attachments, or new
            # requests would share zeroed pages
            self.prefix_cache.clear()
            self.scheduler.detach_waiting_prefixes()
        while self.scheduler.running:
            self.scheduler._evict_victim(now)
        # outbox/pending-offer requests hold pages the loss consumed
        # too: withdraw their offers and requeue for full re-prefill
        for key, (req, _) in list(self._pending_handoff.items()):
            self.handoff.withdraw(key)
            self.stats["handoff_expired"] += 1
            self.scheduler.requeue_handoff(req, now=now)
        self._pending_handoff = {}
        for req in self._handoff_outbox:
            self.scheduler.requeue_handoff(req, now=now)
        self._handoff_outbox = []

    # ------------------------------------------------------------------
    # disaggregated prefill/decode handoff (docs/inference.md)
    # ------------------------------------------------------------------

    def _pool_load(self):
        """The load gauge this pool announces: backlog plus page-pool
        occupancy (the fraction breaks ties between pools with equal
        request counts) — the prefill side's least-loaded dst pick and
        the router's weighted score both consume it."""
        usable = max(self.cache.num_pages - 1, 1)
        return (len(self.scheduler.running) +
                len(self.scheduler.waiting) +
                len(self.scheduler.quarantined) +
                len(self._handoff_outbox) + len(self._pending_handoff) +
                (1.0 - self.cache.num_free / usable))

    def _collect_handoffs(self):
        """Move every running sequence with a sampled token out of the
        scheduler into the handoff outbox (prefill role only). The
        request keeps its pages (freed on the accepted ack) but stops
        being schedulable here — its decode happens on the other
        pool."""
        moved = [r for r in self.scheduler.running if r.generated]
        for req in moved:
            self.scheduler.running.remove(req)
            self._handoff_outbox.append(req)

    def _encode_handoff(self, req, now):
        """One offer payload: the page bytes (`encode_pages` — int8
        scales included) plus the request metadata the decode pool
        rebuilds the `Request` from. Clocks do not cross the wire —
        the deadline travels as REMAINING milliseconds."""
        payload = encode_pages(self.cache, req.pages)
        deadline_remaining_ms = None
        if req.deadline_at is not None:
            deadline_remaining_ms = (req.deadline_at - now) * 1e3
        payload["request"] = {
            "request_id": req.request_id,
            "prompt": [int(t) for t in req.prompt],
            "generated": [int(t) for t in req.generated],
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": req.eos_token_id,
            "priority": req.priority,
            "deadline_remaining_ms": deadline_remaining_ms,
            "ttft_slo_ms": req.ttft_slo_ms,
            "cached": int(req.cached),
            "evictions": int(req.evictions),
        }
        return payload

    def _dispatch_handoffs(self, now):
        """Offer every outbox request to the least-loaded announced
        decode pool. No decode pool announced yet → the outbox simply
        waits (the requests hold their pages and re-offer next step)."""
        if not self._handoff_outbox:
            return
        dst = self.handoff.choose_decode_pool()
        if dst is None:
            return
        for req in self._handoff_outbox:
            key = self.handoff.offer(dst, str(req.request_id),
                                     self._encode_handoff(req, now))
            self._pending_handoff[key] = (req, now)
            self.stats["handoff_sent"] += 1
        self._handoff_outbox = []

    def _poll_handoff_acks(self, now):
        """Prefill-side verdict sweep: free pages on ``accepted``
        (the decode pool owns the sequence now), requeue with eviction
        semantics on ``rejected``, and withdraw + requeue offers older
        than ``handoff_timeout_s`` (a late ack for a withdrawn offer is
        dropped as stale)."""
        for key, _, payload in self.handoff.poll_acks():
            entry = self._pending_handoff.pop(key, None)
            self.handoff.retire(key)
            if entry is None:
                continue               # ack for a withdrawn offer
            req, offered_at = entry
            if payload.get("state") == ACCEPTED:
                self.stats["handoff_acked"] += 1
                self.request_metrics.observe_handoff(now - offered_at)
                # registry-shared pages just lose this request's ref
                self.scheduler._release_pages(req)
                req.state = FINISHED
            else:
                self.stats["handoff_rejected"] += 1
                self.scheduler.requeue_handoff(req, now=now)
        for key, (req, offered_at) in list(self._pending_handoff.items()):
            if now - offered_at <= self.handoff_timeout_s:
                continue
            del self._pending_handoff[key]
            self.handoff.withdraw(key)
            self.stats["handoff_expired"] += 1
            self.scheduler.requeue_handoff(req, now=now)

    def _install_handoffs(self, now):
        """Decode-side sweep: install every offer addressed to this
        pool, acking each with its verdict (the ack overwrites the
        offer slot — the page bytes never outlive one trip)."""
        for key, payload in self.handoff.poll_offers():
            try:
                self._install_handoff(payload, now)
            except HandoffRejected as e:
                self.stats["handoff_refused"] += 1
                self.handoff.ack(key, ok=False, reason=e.reason)
            else:
                self.stats["handoff_installed"] += 1
                self.handoff.ack(key, ok=True)

    def _install_handoff(self, payload, now):
        """Land one offered request in this pool: geometry/capacity
        checks, prefix-cache dedupe (chain pages this pool already
        holds are retained, not rewritten), page allocation + batched
        scatter, then mid-stream admission straight into `running` —
        sampled tokens, priority, and remaining deadline intact. Raises
        typed `HandoffRejected`; every rejection path leaves this
        pool's free list and refcounts exactly as it found them."""
        if self._handoff_draining or self._drain_requested:
            raise HandoffRejected(
                f"pool {self.pool_id!r} is draining", reason="draining")
        if len(self.scheduler.running) >= self.max_batch_size:
            raise HandoffRejected(
                f"pool {self.pool_id!r} decode batch is full "
                f"({self.max_batch_size})", reason="busy")
        check_geometry(self.cache, payload)
        meta = payload["request"]
        prompt = [int(t) for t in meta["prompt"]]
        shared_pages, prefix_node = [], None
        if self.prefix_cache is not None:
            chain = self.prefix_cache.lookup(prompt)
            if chain:
                shared_pages = [n.page for n in chain]
                prefix_node = chain[-1]
        n_shared = len(shared_pages)
        # retain the chain BEFORE allocating: an allocation-shortfall
        # reclaim sweep skips pages with live request references, so
        # the matched chain cannot be reclaimed out from under us
        self.cache.retain(shared_pages)
        own = self.cache.allocate(payload["n"] - n_shared)
        if own is None:
            self.cache.free(shared_pages)
            raise HandoffRejected(
                f"pool {self.pool_id!r} has no room for "
                f"{payload['n'] - n_shared} page(s)", reason="pool_full")
        try:
            write_pages(self.cache, own, payload, skip=n_shared)
        except HandoffRejected:
            self.cache.free(own + shared_pages)
            raise
        req = Request(
            prompt=prompt,
            max_new_tokens=int(meta["max_new_tokens"]),
            eos_token_id=meta["eos_token_id"],
            request_id=meta["request_id"],
            priority=meta.get("priority", self.default_priority),
            ttft_slo_ms=meta.get("ttft_slo_ms"),
            generated=[int(t) for t in meta["generated"]],
            pages=shared_pages + own,
            cached=int(meta["cached"]),
            n_shared=n_shared,
            prefix_node=prefix_node,
            evictions=int(meta.get("evictions", 0)),
            # TTFT was observed ONCE, on the prefill pool: a non-None
            # first_token_at blocks any re-count here (a local eviction
            # re-prefill included); inter-token starts at install
            submitted_at=now, first_token_at=now, last_token_at=now)
        remaining_ms = meta.get("deadline_remaining_ms")
        if remaining_ms is not None:
            req.deadline_ms = float(remaining_ms)
            req.deadline_at = now + float(remaining_ms) / 1e3
        self.scheduler.admit_handoff(req, now=now)
        if self.prefix_cache is not None:
            self.scheduler._register_prefix(req)
        return req

    def _programs_warm(self, plan):
        """True when every compiled program this plan dispatches has
        at least one executable — the watchdog must not count a
        first-call XLA compile as a hang (the PR 4 discipline)."""
        def warm(key):
            fn = self._compiled.get(key)
            if fn is None:
                return False
            return (fn._cache_size() if hasattr(fn, "_cache_size")
                    else 1) >= 1
        if plan.empty:
            return False
        if plan.prefills:
            B, S = plan.prefill_batch, plan.prefill_len
            pkey = (("chunk", "target", "sample", B, S)
                    if plan.prefill_kind == "chunk"
                    else ("prefill", B, S))
            if not warm(pkey):
                return False
            if self.spec_k and not warm(("chunk", "draft", "write", B, S)):
                return False
        if plan.decodes:
            B = plan.decode_batch
            if self.spec_k:
                if not warm(("spec_propose", B)) or not warm(
                        ("chunk", "target", "verify", B, self.spec_k + 1)):
                    return False
            elif not warm(("decode", B)):
                return False
        return True

    def _on_serving_hang(self):
        """Watchdog expiry (watchdog thread): the serving step blew its
        wall-clock deadline. Dump every thread's stack, then request a
        drain-style emergency flush — admissions stop NOW (flag write,
        async-signal-safe) and `run()` performs the full drain + typed
        in-flight failure + metrics flush if/when the stuck step
        returns."""
        from ..runtime.sentinel import dump_all_stacks
        self.watchdog_fires += 1
        self.last_stack_dump = dump_all_stacks()
        logger.error(
            f"serving hang watchdog: step exceeded "
            f"{self.watchdog.timeout_s:.1f}s — requesting an emergency "
            f"drain; all-thread stacks:\n{self.last_stack_dump}")
        self._drain_requested = True
        try:
            if self.monitor is not None:
                self.monitor.flush()
        except Exception:  # noqa: BLE001 - best-effort from the thread
            pass

    def _record_request_spans(self, plan):
        """Per-request lifecycle records behind the telemetry capture
        machinery: while a capture window is open, every request that
        FINISHED this step lands in the span buffer as one event
        covering submit → last token (exported in the Chrome trace next
        to the schedule/prefill/decode spans). Zero cost outside a
        window."""
        tracer = getattr(self.telemetry, "tracer", None)
        if tracer is None or not tracer.capturing:
            return
        now = time.perf_counter()
        for req in plan.prefills + plan.decodes:
            if req.state == FINISHED and req.submitted_at is not None:
                tracer.record_event(
                    f"request/{req.request_id}", req.submitted_at,
                    (req.last_token_at or now) - req.submitted_at)

    def _chunk_arrays(self, reqs, B, S):
        """Window inputs for the chunk programs: each request's suffix
        (everything past its shared prefix pages — the whole context
        when nothing is shared) at its absolute positions, plus the
        full-width page table (shared pages included: the window
        attends over the prefix K/V it did not write)."""
        tokens = np.zeros((B, S), np.int32)
        start = np.zeros((B,), np.int32)
        n_new = np.zeros((B,), np.int32)
        page_table = np.zeros((B, self.n_pages_max), np.int32)
        for i, req in enumerate(reqs):
            shared = req.n_shared * self.page_size
            suffix = req.context[shared:]
            tokens[i, :len(suffix)] = suffix
            start[i] = shared
            n_new[i] = len(suffix)
            page_table[i, :len(req.pages)] = req.pages
        return tokens, start, n_new, page_table

    def _draft_prefill_twin(self, reqs, B, S):
        """Mirror a prefill into the draft pools (speculation on): the
        draft's K/V for every newly written position lands at the SAME
        page ids, so the next propose step attends over a complete
        draft view of the sequence. Shared prefix pages already hold
        the registrant's draft K/V and are not rewritten. The rng slot
        is dead in write mode — a constant key keeps the target
        sampling stream identical to a non-speculative run."""
        tokens, start, n_new, pt = self._chunk_arrays(reqs, B, S)
        fn = self._chunk_fn(B, S, "draft", "write")
        self.draft_cache.k, self.draft_cache.v = fn(
            self.draft_params, self.draft_stacked, jnp.asarray(tokens),
            jnp.asarray(start), jnp.asarray(n_new), jnp.asarray(pt),
            self.draft_cache.k, self.draft_cache.v, jax.random.PRNGKey(0))

    def _complete_prefills(self, reqs, nxt):
        now = time.perf_counter()
        for i, req in enumerate(reqs):
            self.scheduler.complete_prefill(req, int(nxt[i]))
            # TTFT: once per request, from the ORIGINAL submit — an
            # evicted request's re-prefill resamples a token it already
            # delivered and must not re-count
            if req.first_token_at is None and req.submitted_at is not None:
                req.first_token_at = now
                ttft_s = now - req.submitted_at
                self.request_metrics.observe_ttft(ttft_s)
                if self.admission is not None:
                    # the shedding signal: measured TTFT EMA vs SLOs
                    self.admission.observe_ttft(ttft_s * 1e3)
            req.last_token_at = now

    def _run_prefill(self, plan):
        B, S = plan.prefill_batch, plan.prefill_len
        if plan.prefill_kind == "chunk":
            # prefix-cache hit batch: suffix-only window through the
            # chunk program (the full-prefill scatter would overwrite
            # the shared pages other requests are reading)
            tokens, start, n_new, pt = self._chunk_arrays(
                plan.prefills, B, S)
            fn = self._chunk_fn(B, S, "target", "sample")
            nxt, self.cache.k, self.cache.v = fn(
                self.params, self.params_stacked, jnp.asarray(tokens),
                jnp.asarray(start), jnp.asarray(n_new), jnp.asarray(pt),
                self.cache.k, self.cache.v, self._next_rng())
        else:
            n_pages_row = S // self.page_size
            tokens = np.zeros((B, S), np.int32)
            lengths = np.zeros((B,), np.int32)
            page_table = np.zeros((B, n_pages_row), np.int32)
            for i, req in enumerate(plan.prefills):
                ctx = req.context
                tokens[i, :len(ctx)] = ctx
                lengths[i] = len(ctx)
                page_table[i, :len(req.pages)] = req.pages
            fn = self._prefill_fn(B, S)
            nxt, self.cache.k, self.cache.v = fn(
                self.params, self.params_stacked, jnp.asarray(tokens),
                jnp.asarray(lengths), jnp.asarray(page_table), self.cache.k,
                self.cache.v, self._next_rng())
        if self.spec_k:
            self._draft_prefill_twin(plan.prefills, B, S)
        self._complete_prefills(plan.prefills, np.asarray(nxt))

    def _run_decode(self, plan):
        B = plan.decode_batch
        tokens = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        page_table = np.zeros((B, self.n_pages_max), np.int32)
        for i, req in enumerate(plan.decodes):
            tokens[i] = req.generated[-1]
            lengths[i] = req.cached + 1
            page_table[i, :len(req.pages)] = req.pages
        fn = self._decode_fn(B)
        nxt, self.cache.k, self.cache.v = fn(
            self.params, self.params_stacked, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(page_table), self.cache.k,
            self.cache.v, self._next_rng())
        nxt = np.asarray(nxt)
        now = time.perf_counter()
        for i, req in enumerate(plan.decodes):
            self.scheduler.complete_decode(req, int(nxt[i]))
            if req.last_token_at is not None:
                self.request_metrics.observe_inter_token(
                    now - req.last_token_at)
            req.last_token_at = now
        return len(plan.decodes)

    # ------------------------------------------------------------------
    # speculative decoding (docs/inference.md "Speculative decoding")
    # ------------------------------------------------------------------

    @staticmethod
    def _accept_greedy(tgt, proposed, w):
        """Greedy acceptance: `tgt[j]` (the verify forward's argmax at
        window index j) IS the token sequential greedy decode would
        produce there — proposals only decide how many of them land in
        one step. Accept while the draft agrees; the first disagreement
        appends the target's correction and stops; full agreement earns
        the bonus token `tgt[w]`. Token-identical to non-speculative
        greedy decode by construction (pinned by test)."""
        out = []
        for j in range(w):
            out.append(int(tgt[j]))
            if int(proposed[j]) != int(tgt[j]):
                return out
        out.append(int(tgt[w]))
        return out

    def _accept_sampled(self, probs, proposed, w):
        """Rejection-sampling acceptance against the target's
        temperature-scaled distributions (`probs` [S, V] fp32). The
        draft proposes greedily — a delta distribution q = δ(x) — so
        the standard accept test `u < p(x)/q(x)` reduces to `u < p(x)`
        and the residual (p - q)⁺ to p with x zeroed: each emitted
        token is distributed exactly as sequential sampling from p,
        whatever the draft proposed."""
        out = []
        for j in range(w):
            p = np.asarray(probs[j], np.float64)
            x = int(proposed[j])
            if self._spec_rng.random() < p[x]:
                out.append(x)
                continue
            p[x] = 0.0
            total = p.sum()
            if total <= 0.0:
                out.append(x)     # p WAS the delta at x: accept it
            else:
                out.append(int(self._spec_rng.choice(len(p),
                                                     p=p / total)))
            return out
        p = np.asarray(probs[w], np.float64)
        out.append(int(self._spec_rng.choice(len(p), p=p / p.sum())))
        return out

    def _run_speculative(self, plan):
        """One speculative decode step: the draft proposes up to k
        tokens per row, the target verifies the whole window in ONE
        chunk forward, and acceptance appends 1..k+1 tokens per row.
        Pages grown for tokens the shrinking window will never reach
        roll back through the allocator (`_rollback_spec_pages`).
        Returns the number of tokens appended across the batch."""
        B = plan.decode_batch
        reqs = plan.decodes
        tokens = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        windows = np.full((B,), -1, np.int32)
        page_table = np.zeros((B, self.n_pages_max), np.int32)
        for i, req in enumerate(reqs):
            tokens[i] = req.generated[-1]
            lengths[i] = req.cached + 1
            windows[i] = self.scheduler._spec_window(req)
            page_table[i, :len(req.pages)] = req.pages
        pt = jnp.asarray(page_table)
        fn = self._propose_fn(B)
        proposed, self.draft_cache.k, self.draft_cache.v = fn(
            self.draft_params, self.draft_stacked, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(windows), pt,
            self.draft_cache.k, self.draft_cache.v)
        proposed = np.asarray(proposed)

        # verify window per row: [pending token, proposals[:w]] at
        # positions cached..cached+w — the pending token's K/V enters
        # the target cache here, exactly like a plain decode step
        S = self.spec_k + 1
        wtokens = np.zeros((B, S), np.int32)
        n_new = np.zeros((B,), np.int32)
        for i in range(len(reqs)):
            w = int(windows[i])
            wtokens[i, 0] = tokens[i]
            wtokens[i, 1:1 + w] = proposed[i, :w]
            n_new[i] = w + 1
        start = np.maximum(lengths - 1, 0).astype(np.int32)
        vfn = self._chunk_fn(B, S, "target", "verify")
        out, self.cache.k, self.cache.v = vfn(
            self.params, self.params_stacked, jnp.asarray(wtokens),
            jnp.asarray(start), jnp.asarray(n_new), pt, self.cache.k,
            self.cache.v, self._next_rng())
        out = np.asarray(out)

        now = time.perf_counter()
        produced = 0
        for i, req in enumerate(reqs):
            w = int(windows[i])
            if self.temperature <= 0.0:
                accepted = self._accept_greedy(out[i], proposed[i], w)
            else:
                accepted = self._accept_sampled(out[i], proposed[i], w)
            self.stats["spec_proposed"] += w
            self.stats["spec_accepted"] += len(accepted) - 1
            appended = self.scheduler.complete_speculative(req, accepted)
            produced += appended
            if req.last_token_at is not None and appended:
                # the user-visible cadence: one step emitted `appended`
                # tokens, so each token's inter-token gap is dt/appended
                per_token = (now - req.last_token_at) / appended
                for _ in range(appended):
                    self.request_metrics.observe_inter_token(per_token)
            req.last_token_at = now
        self.stats["spec_steps"] += 1
        return produced

    # ------------------------------------------------------------------
    # graceful drain (SIGTERM from the pod scheduler)
    # ------------------------------------------------------------------
    #
    # Serving must NOT inherit the training engine's emergency-save
    # handler semantics: there is no state worth checkpointing mid-
    # decode, and dying mid-step wastes every in-flight sequence. The
    # right shutdown is: stop admitting, finish what's running (bounded
    # by `inference.drain_deadline_s`), flush the Serve/* telemetry,
    # exit 0 so the orchestrator sees a clean termination.

    def install_drain_handler(self):
        """Register SIGTERM/SIGINT to REQUEST a drain (flag only — the
        same async-signal-safe discipline as the training preemption
        handler); `run()` performs the actual drain at its next loop
        iteration. Weakly bound: the signal registry must not pin the
        engine (and its page pools) for the process lifetime."""
        import signal as _signal
        import threading
        import weakref
        if threading.current_thread() is not threading.main_thread():
            return self
        engine_ref = weakref.ref(self)

        def handler(signum, frame):  # noqa: ARG001
            engine = engine_ref()
            if engine is not None:
                engine._drain_requested = True
                engine._drain_signum = signum

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                self._prev_handlers[sig] = _signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
        return self

    def restore_signal_handlers(self):
        import signal as _signal
        for sig, handler in self._prev_handlers.items():
            try:
                _signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev_handlers = {}

    def request_drain(self):
        """Programmatic equivalent of the SIGTERM handler."""
        self._drain_requested = True

    def drain(self, deadline_s=None):
        """Stop admissions, finish in-flight sequences for at most
        `deadline_s` (config `inference.drain_deadline_s` by default),
        then flush Serve/* telemetry. Returns a summary dict; fresh
        queued requests are left unserved (`unserved` counts them) for
        the replacement instance.

        When the deadline elapses, still-in-flight requests are FAILED
        with a typed `DrainAborted` terminal status and flushed to the
        metrics before the process exits — previously they were
        silently abandoned, so a client could never distinguish a
        drain from a crash."""
        deadline_s = (self.drain_deadline_s if deadline_s is None
                      else float(deadline_s))
        self.scheduler.stop_admissions()
        # a draining decode pool refuses fresh handoff offers (typed
        # ``draining`` rejection — the prefill side re-offers to a
        # surviving pool); a draining prefill pool still steps until
        # its outbox and pending offers resolve
        self._handoff_draining = True
        t0 = time.perf_counter()
        deadline_hit = False
        while (self.scheduler.has_inflight_work or
               self._handoff_outbox or self._pending_handoff):
            if time.perf_counter() - t0 > deadline_s:
                deadline_hit = True
                break
            self.step()
        abandoned = 0
        for key, (req, _) in list(self._pending_handoff.items()):
            self.handoff.withdraw(key)
            self.scheduler.finish_failed(req, DrainAborted(
                f"graceful-drain deadline ({deadline_s:.1f}s) elapsed "
                f"with request {req.request_id}'s handoff offer still "
                f"unacked", attempts=req.failures))
            abandoned += 1
        self._pending_handoff = {}
        for req in self._handoff_outbox:
            self.scheduler.finish_failed(req, DrainAborted(
                f"graceful-drain deadline ({deadline_s:.1f}s) elapsed "
                f"with request {req.request_id} still awaiting a decode "
                f"pool", attempts=req.failures))
            abandoned += 1
        self._handoff_outbox = []
        for req in self.scheduler.inflight_requests():
            self.scheduler.finish_failed(req, DrainAborted(
                f"graceful-drain deadline ({deadline_s:.1f}s) elapsed "
                f"with request {req.request_id} still in flight "
                f"({len(req.generated)}/{req.max_new_tokens} tokens "
                f"generated)", attempts=req.failures))
            abandoned += 1
        self._sync_status_counts()
        summary = {
            "drained_s": time.perf_counter() - t0,
            "deadline_hit": deadline_hit,
            "inflight_abandoned": abandoned,
            "unserved": sum(1 for r in self.scheduler.waiting
                            if not r.evictions),
        }
        self.serve_stats()          # pushes Serve/* scalars (incl. the
        # per-status terminal counters — the DrainAborted failures land
        # in Serve/requests_failed BEFORE the monitor closes)
        if self.monitor is not None:
            if self._owns_monitor:
                self.monitor.close()  # drain the buffered scalar queue
            else:
                # borrowed from a co-resident training engine: flush the
                # Serve/* scalars but leave it open for Train/* records
                flush = getattr(self.monitor, "flush", None)
                if flush is not None:
                    flush()
        self.telemetry.close()
        self.restore_signal_handlers()
        logger.info(f"inference drain complete: {summary}")
        return summary

    def run(self, max_steps=None):
        """Drive steps until the queue drains (or `max_steps`). A
        pending drain request (SIGTERM via `install_drain_handler`, or
        `request_drain()`) switches to the graceful-drain path and exits
        the process with code 0 once in-flight work is finished — also
        on an IDLE server (nothing in flight ⇒ the drain is just the
        telemetry flush + exit; the SIGTERM contract must not depend on
        traffic being present)."""
        steps = 0
        while True:
            if self._drain_requested:
                self.drain()
                raise SystemExit(0)
            if not (self.scheduler.has_work or self._handoff_outbox or
                    self._pending_handoff):
                break
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    def generate(self, prompts, max_new_tokens, eos_token_id=None):
        """Batch convenience: submit every prompt, drain the queue, and
        return the generated token lists in submission order. Consumes
        `scheduler.pop_finished()` (including any requests already
        finished by earlier manual `step()` driving), so the finished
        list cannot grow across repeated calls."""
        ids = [self.submit(p, max_new_tokens, eos_token_id=eos_token_id)
               for p in prompts]
        done = {}
        while self.scheduler.has_work:
            self.step()
            for r in self.scheduler.pop_finished():
                done[r.request_id] = r
        return [list(done[i].generated) for i in ids]

    def generate_rollouts(self, prompts, max_new_tokens, eos_token_id=None):
        """RL rollout batch API (docs/rl.md): `generate` plus the
        throughput/speculation accounting the driver's `Train/RL/*`
        scalars and the bench row need. Returns ``(outputs, stats)``
        where ``outputs[i]`` is prompt ``i``'s generated token list and
        ``stats`` carries rollout wall time, generated-token counts and
        the serve-side deltas (compile count, spec acceptance) for THIS
        call only."""
        before = {"compile": self.compile_count(),
                  "spec_proposed": self.stats["spec_proposed"],
                  "spec_accepted": self.stats["spec_accepted"]}
        t0 = time.perf_counter()
        outputs = self.generate(prompts, max_new_tokens,
                                eos_token_id=eos_token_id)
        rollout_s = time.perf_counter() - t0
        tokens = sum(len(o) for o in outputs)
        stats = {
            "rollout_s": rollout_s,
            "rollout_tokens": tokens,
            "tokens_per_s": tokens / max(rollout_s, 1e-9),
            "compile_delta": self.compile_count() - before["compile"],
        }
        if self.spec_k:
            proposed = self.stats["spec_proposed"] - before["spec_proposed"]
            accepted = self.stats["spec_accepted"] - before["spec_accepted"]
            stats["spec_acceptance_rate"] = accepted / max(proposed, 1)
        return outputs, stats

    def serve_stats(self):
        """Counters + phase seconds + request-latency percentiles
        (p50/p99 of admission wait / TTFT / inter-token, from the
        fixed-bucket histograms); also pushed to the monitor (as
        ``Serve/*`` scalars keyed by total generated tokens) when one
        was attached."""
        out = dict(self.stats)
        out.update(self.request_metrics.summary())
        if self.prefix_cache is not None:
            pcs = self.prefix_cache.stats
            out["prefix_lookups"] = pcs["lookups"]
            out["prefix_hits"] = pcs["hits"]
            out["prefix_hit_rate"] = pcs["hits"] / max(pcs["lookups"], 1)
            out["prefix_pages_shared"] = pcs["pages_shared"]
            out["prefix_saved_prefill_tokens"] = \
                pcs["saved_prefill_tokens"]
        if self.spec_k:
            out["spec_acceptance_rate"] = self.stats["spec_accepted"] / \
                max(self.stats["spec_proposed"], 1)
        total = out["prefill_tokens"] + out["decode_tokens"]
        if self.monitor is not None:
            self.monitor.record(
                total, {f"Serve/{k}": float(v) for k, v in out.items()})
        return out
