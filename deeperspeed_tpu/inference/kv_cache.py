"""Preallocated, mesh-sharded paged KV cache for the serving engine.

Layout: one K and one V pool per model, ``[L, P, H, page_size, D]``
(layers, pool pages, heads, slots per page, head dim) — head-major so a
model-parallel mesh shards dim 2 (heads) over the ``model`` axis. One
page id addresses the same page row in EVERY layer and every head
shard, so the allocator is mesh- and layer-agnostic, and decode
attention (head-independent) needs no collective.

Page 0 is RESERVED as the trash page: the allocator never hands it out,
schedulers pad dead page-table entries with it, and inactive batch rows
write their (masked) K/V there. That turns "row is padding" into plain
data flow — no dynamic shapes, no per-row programs.

Allocation is host-side (scheduling is host-side anyway): a free list of
page ids plus a per-page REFERENCE COUNT. `allocate` hands a page out
with one reference; `retain` adds references (prefix-cache sharing);
`free` drops one reference per occurrence and returns the page to the
free list only at zero. Dropping more references than are held —
duplicates within one call included — raises with the offending page
id instead of silently corrupting the free list. The device arrays are
functional jax values — the engine rebinds them after every compiled
prefill/decode call (donated, so XLA updates in place).

**Prefix registry** (`PrefixCache`): a radix-style tree over full
prompt pages. Each node is keyed by the chain (parent node, exact page
token ids) — Python's dict hashing gives the "chained content hash"
with full-key verification, so two different prefixes can never
collide into the same cached page. A registered page carries one
registry-owned reference; new requests whose prompt walks an existing
chain `retain` those pages and skip re-prefilling them. Determinism
makes this sound: given fixed weights, a page's K/V (int8 quantization
included — pinned by test) is a pure function of the token prefix, so
any request's pages are interchangeable with the original's.

**Int8 pages** (``kv_cache_dtype: "int8"``): each pool becomes a
`QuantizedPages` pytree — the int8 data pool plus a per-page SCALE pool
``[L, P, H, page_size]`` (one bf16 scale per head-slot, stored page-row
aligned so the decode kernel resolves both through the same page-table
LUT). K/V vectors quantize symmetrically per (head, slot) at write time;
the decode-attention kernel dequantizes at the DMA boundary
(`ops/pallas/decode_attention.py`). A resident token costs
``2·L·H·(D + 2)`` bytes instead of ``2·L·H·D·2`` at bf16 — ~1.94× more
sessions at a fixed pool budget for D = 64 (bf16 scales deliberately:
fp32 would cost D + 4 and cap the ratio at 1.88×)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS


class QuantizedPages:
    """Int8 page pool + its per-page scale pool, as a pytree node: the
    engine's compiled calls donate/rebind it like a plain pool array
    (both leaves ride every jit/scan/vmap unchanged)."""

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def shape(self):
        return self.data.shape

    def __repr__(self):
        return (f"QuantizedPages(shape={tuple(self.data.shape)}, "
                f"scale={tuple(self.scale.shape)})")


jax.tree_util.register_pytree_node(
    QuantizedPages,
    lambda qp: ((qp.data, qp.scale), None),
    lambda _, children: QuantizedPages(*children))


KV_QMAX = 127.0


def quantize_kv(vec):
    """Symmetric per-vector int8 quantization over the trailing (head
    dim) axis: returns (q int8, scale fp32 [...]) with
    ``dequant = q · scale[..., None]``. Zero vectors keep scale 1."""
    amax = jnp.max(jnp.abs(vec.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / KV_QMAX, 1.0)
    q = jnp.clip(jnp.round(vec.astype(jnp.float32) / scale[..., None]),
                 -KV_QMAX, KV_QMAX).astype(jnp.int8)
    return q, scale


def pages_for_tokens(n_tokens, page_size):
    """Pages needed to hold n_tokens (ceil division)."""
    return -(-int(n_tokens) // int(page_size))


class PagedKVCache:
    """The pooled K/V store plus its free-list allocator.

    ``num_pages`` includes the reserved trash page 0, so the usable pool
    is ``num_pages - 1`` pages = ``(num_pages - 1) * page_size`` tokens
    per layer.
    """

    def __init__(self, num_layers, num_pages, num_heads, page_size,
                 head_dim, dtype=jnp.bfloat16, mesh=None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {num_pages}")
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.num_heads = int(num_heads)
        self.page_size = int(page_size)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.quantized = jnp.dtype(dtype) == jnp.int8
        self.mesh = mesh
        self.sharding = None
        self.scale_sharding = None
        if mesh is not None and MODEL_AXIS in mesh.axis_names and \
                mesh.shape[MODEL_AXIS] > 1:
            if self.num_heads % mesh.shape[MODEL_AXIS]:
                raise ValueError(
                    f"num_heads {self.num_heads} must divide over the "
                    f"'{MODEL_AXIS}' mesh axis "
                    f"({mesh.shape[MODEL_AXIS]} shards)")
            self.sharding = NamedSharding(
                mesh, P(None, None, MODEL_AXIS, None, None))
            self.scale_sharding = NamedSharding(
                mesh, P(None, None, MODEL_AXIS, None))
        self.k = self._make_pool()
        self.v = self._make_pool()
        # free list: every page except the trash page, low ids first so
        # tests are deterministic
        self._free = list(range(self.num_pages - 1, 0, -1))
        # reference counts for allocated pages (absent = free); the
        # prefix registry and co-reading requests hold extra references
        self._refcount = {}
        # optional `PrefixCache` (set by its constructor): allocation
        # shortfalls reclaim LRU unshared registry pages before failing
        self.prefix_cache = None

    def _make_pool(self):
        shape = (self.num_layers, self.num_pages, self.num_heads,
                 self.page_size, self.head_dim)
        data = jnp.zeros(shape, self.dtype)
        if self.sharding is not None:
            data = jax.device_put(data, self.sharding)
        if not self.quantized:
            return data
        # unit scales on the zero pool: dequant of the trash page stays
        # exact zero, and a scale of 0 could never be divided back in.
        # bf16 scales: the scale's relative rounding (2^-9) is noise
        # under the int8 mantissa (2^-7), and fp32 scales would eat the
        # capacity win at head_dim 64 (128/68 = 1.88× vs 128/66 = 1.94×)
        scale = jnp.ones(shape[:-1], jnp.bfloat16)
        if self.scale_sharding is not None:
            scale = jax.device_put(scale, self.scale_sharding)
        return QuantizedPages(data, scale)

    def data_array(self, pool):
        """The raw data leaf of a pool (the array itself when the cache
        is not quantized) — liveness checks poke this."""
        return pool.data if isinstance(pool, QuantizedPages) else pool

    def reset_pools(self):
        """Rebuild the K/V device pools zeroed, keeping the allocator
        state. The serving engine's quarantine path calls this when a
        compiled step died MID-EXECUTION with the pools donated (the
        buffers are consumed and unusable); the engine then re-prefills
        every running sequence, so the zeroed contents are never
        read."""
        self.k = self._make_pool()
        self.v = self._make_pool()

    # -- allocator (host-side) --------------------------------------------

    @property
    def num_free(self):
        return len(self._free)

    @property
    def tokens_capacity(self):
        return self.num_free * self.page_size

    def allocate(self, n):
        """Pop n pages from the free list (each carrying ONE
        reference), or None when fewer remain (all-or-nothing: a
        partial grab would deadlock admission). A shortfall first asks
        the prefix registry to reclaim LRU unshared pages."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free) and self.prefix_cache is not None:
            self.prefix_cache.reclaim(n - len(self._free))
        if n > len(self._free):
            return None
        if n == 0:
            return []
        pages, self._free = self._free[-n:][::-1], self._free[:-n]
        for p in pages:
            self._refcount[p] = 1
        return pages

    def retain(self, pages):
        """Add one reference to each page (prefix-cache sharing: the
        new reader frees through the ordinary `free` path). Pages must
        be currently allocated."""
        pages = [int(p) for p in pages]
        for p in pages:
            if p not in self._refcount:
                raise ValueError(
                    f"cannot retain page {p}: not currently allocated")
        for p in pages:
            self._refcount[p] += 1

    def refcount(self, page):
        """Current reference count of a page (0 = free)."""
        return self._refcount.get(int(page), 0)

    def free(self, pages):
        """Drop one reference per occurrence; a page returns to the
        free list at zero. Raises — BEFORE mutating anything — when a
        call would take any page below zero references: duplicates
        within one call and double-frees across calls both name the
        offending page id (free-list corruption was silent before)."""
        counts = {}
        for p in pages:
            p = int(p)
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"page {p} is not an allocatable id")
            counts[p] = counts.get(p, 0) + 1
        for p, n in counts.items():
            held = self._refcount.get(p, 0)
            if n > held:
                raise ValueError(
                    f"double free of page {p}: {n} release(s) in one "
                    f"call against {held} held reference(s)")
        for p, n in counts.items():
            left = self._refcount[p] - n
            if left:
                self._refcount[p] = left
            else:
                del self._refcount[p]
                self._free.append(p)

    def bytes_per_token(self):
        """K + V bytes of cache one token occupies across all layers
        (int8 pools count the per-slot bf16 scale)."""
        itemsize = jnp.dtype(self.dtype).itemsize
        per_head = self.head_dim * itemsize + (2 if self.quantized else 0)
        return 2 * self.num_layers * self.num_heads * per_head


class _PrefixNode:
    """One registered full page: keyed under its parent by the page's
    exact token ids, so the (parent, key) chain IS the chained content
    hash — dict lookup hashes it, equality verifies it."""

    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key, page, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}
        self.last_used = 0


class PrefixCache:
    """Radix-style page-granular prefix registry over a `PagedKVCache`.

    A completed prefill registers each FULL prompt page as a chain node
    (`register`); a new prompt walks the tree (`lookup`) and shares the
    longest matching page chain via refcounts — prefill then starts at
    the first divergent page. The registry holds one reference per
    registered page, so pages outlive the request that built them;
    `reclaim` releases least-recently-used UNSHARED leaves back to the
    allocator when the pool runs short (or past ``max_pages``), and
    `clear` drops everything (weight hot-swap / pool loss: the cached
    K/V no longer matches what a forward pass would produce).

    Host-side and deterministic: recency is a logical tick counter, not
    wall clock, so the same request stream always caches and reclaims
    the same pages."""

    def __init__(self, cache, max_pages=None):
        self.cache = cache
        self.page_size = cache.page_size
        if max_pages is not None and int(max_pages) < 1:
            raise ValueError(
                f"prefix_cache max_pages must be >= 1, got {max_pages}")
        self.max_pages = None if max_pages is None else int(max_pages)
        self.stats = {"lookups": 0, "hits": 0, "pages_shared": 0,
                      "saved_prefill_tokens": 0, "registered_pages": 0,
                      "reclaimed_pages": 0}
        self._root = _PrefixNode(None, None, None)
        self._pages = 0
        self._tick = 0
        cache.prefix_cache = self

    @staticmethod
    def page_key(tokens):
        """The canonical node key for one page's worth of tokens."""
        return tuple(int(t) for t in tokens)

    def _touch(self, node):
        self._tick += 1
        node.last_used = self._tick

    def lookup(self, tokens):
        """Longest registered page chain covering a prefix of `tokens`,
        capped so at least ONE token is left to prefill (prefill always
        samples the first generated token). Returns the node chain
        (possibly empty); the caller retains the pages."""
        ps = self.page_size
        limit = max((len(tokens) - 1) // ps, 0)
        node = self._root
        chain = []
        for i in range(limit):
            child = node.children.get(
                self.page_key(tokens[i * ps:(i + 1) * ps]))
            if child is None:
                break
            chain.append(child)
            node = child
        for n in chain:
            self._touch(n)
        return chain

    def register(self, parent, keys, pages):
        """Extend the chain under `parent` (None = root) with full
        pages: `keys[i]` is `page_key(...)` of the page's tokens,
        `pages[i]` the request-owned page holding their K/V. A key
        already registered keeps the EXISTING node/page (the request's
        copy stays request-owned and frees normally); a new key retains
        the page for the registry. Returns the deepest node."""
        node = parent if parent is not None else self._root
        for key, page in zip(keys, pages):
            child = node.children.get(key)
            if child is None:
                self.cache.retain([page])
                child = _PrefixNode(key, int(page), node)
                node.children[key] = child
                self._pages += 1
            self._touch(child)
            node = child
        self.stats["registered_pages"] = self._pages
        if self.max_pages is not None and self._pages > self.max_pages:
            self.reclaim(self._pages - self.max_pages)
        return node

    def _lru_leaves(self):
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        out.sort(key=lambda n: n.last_used)
        return out

    def reclaim(self, n_pages):
        """Release up to `n_pages` least-recently-used UNSHARED leaf
        pages back to the allocator (refcount 1 = registry-only; a
        page some in-flight request still reads is never reclaimed —
        "eviction skips shared pages"). Interior nodes become leaves as
        their children go, so a whole cold chain drains back-to-front.
        Returns the number reclaimed."""
        reclaimed = 0
        while reclaimed < int(n_pages):
            leaf = next((l for l in self._lru_leaves()
                         if self.cache.refcount(l.page) == 1), None)
            if leaf is None:
                break
            leaf.parent.children.pop(leaf.key)
            self.cache.free([leaf.page])
            self._pages -= 1
            reclaimed += 1
        self.stats["registered_pages"] = self._pages
        self.stats["reclaimed_pages"] += reclaimed
        return reclaimed

    def clear(self):
        """Drop every chain and release the registry's references.
        Pages still shared with in-flight requests stay allocated until
        those requests free them — only the registry's claim ends."""
        stack = list(self._root.children.values())
        pages = []
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            pages.append(n.page)
        if pages:
            self.cache.free(pages)
        self._root.children.clear()
        self._pages = 0
        self.stats["registered_pages"] = 0
