"""Preallocated, mesh-sharded paged KV cache for the serving engine.

Layout: one K and one V pool per model, ``[L, P, H, page_size, D]``
(layers, pool pages, heads, slots per page, head dim) — head-major so a
model-parallel mesh shards dim 2 (heads) over the ``model`` axis. One
page id addresses the same page row in EVERY layer and every head
shard, so the allocator is mesh- and layer-agnostic, and decode
attention (head-independent) needs no collective.

Page 0 is RESERVED as the trash page: the allocator never hands it out,
schedulers pad dead page-table entries with it, and inactive batch rows
write their (masked) K/V there. That turns "row is padding" into plain
data flow — no dynamic shapes, no per-row programs.

Allocation is host-side (scheduling is host-side anyway): a free list of
page ids. The device arrays are functional jax values — the engine
rebinds them after every compiled prefill/decode call (donated, so XLA
updates in place).

**Int8 pages** (``kv_cache_dtype: "int8"``): each pool becomes a
`QuantizedPages` pytree — the int8 data pool plus a per-page SCALE pool
``[L, P, H, page_size]`` (one bf16 scale per head-slot, stored page-row
aligned so the decode kernel resolves both through the same page-table
LUT). K/V vectors quantize symmetrically per (head, slot) at write time;
the decode-attention kernel dequantizes at the DMA boundary
(`ops/pallas/decode_attention.py`). A resident token costs
``2·L·H·(D + 2)`` bytes instead of ``2·L·H·D·2`` at bf16 — ~1.94× more
sessions at a fixed pool budget for D = 64 (bf16 scales deliberately:
fp32 would cost D + 4 and cap the ratio at 1.88×)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS


class QuantizedPages:
    """Int8 page pool + its per-page scale pool, as a pytree node: the
    engine's compiled calls donate/rebind it like a plain pool array
    (both leaves ride every jit/scan/vmap unchanged)."""

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def shape(self):
        return self.data.shape

    def __repr__(self):
        return (f"QuantizedPages(shape={tuple(self.data.shape)}, "
                f"scale={tuple(self.scale.shape)})")


jax.tree_util.register_pytree_node(
    QuantizedPages,
    lambda qp: ((qp.data, qp.scale), None),
    lambda _, children: QuantizedPages(*children))


KV_QMAX = 127.0


def quantize_kv(vec):
    """Symmetric per-vector int8 quantization over the trailing (head
    dim) axis: returns (q int8, scale fp32 [...]) with
    ``dequant = q · scale[..., None]``. Zero vectors keep scale 1."""
    amax = jnp.max(jnp.abs(vec.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / KV_QMAX, 1.0)
    q = jnp.clip(jnp.round(vec.astype(jnp.float32) / scale[..., None]),
                 -KV_QMAX, KV_QMAX).astype(jnp.int8)
    return q, scale


def pages_for_tokens(n_tokens, page_size):
    """Pages needed to hold n_tokens (ceil division)."""
    return -(-int(n_tokens) // int(page_size))


class PagedKVCache:
    """The pooled K/V store plus its free-list allocator.

    ``num_pages`` includes the reserved trash page 0, so the usable pool
    is ``num_pages - 1`` pages = ``(num_pages - 1) * page_size`` tokens
    per layer.
    """

    def __init__(self, num_layers, num_pages, num_heads, page_size,
                 head_dim, dtype=jnp.bfloat16, mesh=None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {num_pages}")
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.num_heads = int(num_heads)
        self.page_size = int(page_size)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.quantized = jnp.dtype(dtype) == jnp.int8
        self.mesh = mesh
        self.sharding = None
        self.scale_sharding = None
        if mesh is not None and MODEL_AXIS in mesh.axis_names and \
                mesh.shape[MODEL_AXIS] > 1:
            if self.num_heads % mesh.shape[MODEL_AXIS]:
                raise ValueError(
                    f"num_heads {self.num_heads} must divide over the "
                    f"'{MODEL_AXIS}' mesh axis "
                    f"({mesh.shape[MODEL_AXIS]} shards)")
            self.sharding = NamedSharding(
                mesh, P(None, None, MODEL_AXIS, None, None))
            self.scale_sharding = NamedSharding(
                mesh, P(None, None, MODEL_AXIS, None))
        self.k = self._make_pool()
        self.v = self._make_pool()
        # free list: every page except the trash page, low ids first so
        # tests are deterministic
        self._free = list(range(self.num_pages - 1, 0, -1))

    def _make_pool(self):
        shape = (self.num_layers, self.num_pages, self.num_heads,
                 self.page_size, self.head_dim)
        data = jnp.zeros(shape, self.dtype)
        if self.sharding is not None:
            data = jax.device_put(data, self.sharding)
        if not self.quantized:
            return data
        # unit scales on the zero pool: dequant of the trash page stays
        # exact zero, and a scale of 0 could never be divided back in.
        # bf16 scales: the scale's relative rounding (2^-9) is noise
        # under the int8 mantissa (2^-7), and fp32 scales would eat the
        # capacity win at head_dim 64 (128/68 = 1.88× vs 128/66 = 1.94×)
        scale = jnp.ones(shape[:-1], jnp.bfloat16)
        if self.scale_sharding is not None:
            scale = jax.device_put(scale, self.scale_sharding)
        return QuantizedPages(data, scale)

    def data_array(self, pool):
        """The raw data leaf of a pool (the array itself when the cache
        is not quantized) — liveness checks poke this."""
        return pool.data if isinstance(pool, QuantizedPages) else pool

    def reset_pools(self):
        """Rebuild the K/V device pools zeroed, keeping the allocator
        state. The serving engine's quarantine path calls this when a
        compiled step died MID-EXECUTION with the pools donated (the
        buffers are consumed and unusable); the engine then re-prefills
        every running sequence, so the zeroed contents are never
        read."""
        self.k = self._make_pool()
        self.v = self._make_pool()

    # -- allocator (host-side) --------------------------------------------

    @property
    def num_free(self):
        return len(self._free)

    @property
    def tokens_capacity(self):
        return self.num_free * self.page_size

    def allocate(self, n):
        """Pop n pages from the free list, or None when fewer remain
        (all-or-nothing: a partial grab would deadlock admission)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        if n == 0:
            return []
        pages, self._free = self._free[-n:][::-1], self._free[:-n]
        return pages

    def free(self, pages):
        for p in pages:
            p = int(p)
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"page {p} is not an allocatable id")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(int(p) for p in pages)

    def bytes_per_token(self):
        """K + V bytes of cache one token occupies across all layers
        (int8 pools count the per-slot bf16 scale)."""
        itemsize = jnp.dtype(self.dtype).itemsize
        per_head = self.head_dim * itemsize + (2 if self.quantized else 0)
        return 2 * self.num_layers * self.num_heads * per_head
