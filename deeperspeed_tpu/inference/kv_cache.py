"""Preallocated, mesh-sharded paged KV cache for the serving engine.

Layout: one K and one V pool per model, ``[L, P, H, page_size, D]``
(layers, pool pages, heads, slots per page, head dim) — head-major so a
model-parallel mesh shards dim 2 (heads) over the ``model`` axis. One
page id addresses the same page row in EVERY layer and every head
shard, so the allocator is mesh- and layer-agnostic, and decode
attention (head-independent) needs no collective.

Page 0 is RESERVED as the trash page: the allocator never hands it out,
schedulers pad dead page-table entries with it, and inactive batch rows
write their (masked) K/V there. That turns "row is padding" into plain
data flow — no dynamic shapes, no per-row programs.

Allocation is host-side (scheduling is host-side anyway): a free list of
page ids. The device arrays are functional jax values — the engine
rebinds them after every compiled prefill/decode call (donated, so XLA
updates in place).
"""

from jax.sharding import NamedSharding, PartitionSpec as P

import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS


def pages_for_tokens(n_tokens, page_size):
    """Pages needed to hold n_tokens (ceil division)."""
    return -(-int(n_tokens) // int(page_size))


class PagedKVCache:
    """The pooled K/V store plus its free-list allocator.

    ``num_pages`` includes the reserved trash page 0, so the usable pool
    is ``num_pages - 1`` pages = ``(num_pages - 1) * page_size`` tokens
    per layer.
    """

    def __init__(self, num_layers, num_pages, num_heads, page_size,
                 head_dim, dtype=jnp.bfloat16, mesh=None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {num_pages}")
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.num_heads = int(num_heads)
        self.page_size = int(page_size)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.mesh = mesh
        shape = (self.num_layers, self.num_pages, self.num_heads,
                 self.page_size, self.head_dim)
        self.sharding = None
        if mesh is not None and MODEL_AXIS in mesh.axis_names and \
                mesh.shape[MODEL_AXIS] > 1:
            if self.num_heads % mesh.shape[MODEL_AXIS]:
                raise ValueError(
                    f"num_heads {self.num_heads} must divide over the "
                    f"'{MODEL_AXIS}' mesh axis "
                    f"({mesh.shape[MODEL_AXIS]} shards)")
            self.sharding = NamedSharding(
                mesh, P(None, None, MODEL_AXIS, None, None))
        if self.sharding is not None:
            import jax
            self.k = jax.device_put(jnp.zeros(shape, dtype), self.sharding)
            self.v = jax.device_put(jnp.zeros(shape, dtype), self.sharding)
        else:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
        # free list: every page except the trash page, low ids first so
        # tests are deterministic
        self._free = list(range(self.num_pages - 1, 0, -1))

    def reset_pools(self):
        """Rebuild the K/V device pools zeroed, keeping the allocator
        state. The serving engine's quarantine path calls this when a
        compiled step died MID-EXECUTION with the pools donated (the
        buffers are consumed and unusable); the engine then re-prefills
        every running sequence, so the zeroed contents are never
        read."""
        shape = (self.num_layers, self.num_pages, self.num_heads,
                 self.page_size, self.head_dim)
        if self.sharding is not None:
            import jax
            self.k = jax.device_put(jnp.zeros(shape, self.dtype),
                                    self.sharding)
            self.v = jax.device_put(jnp.zeros(shape, self.dtype),
                                    self.sharding)
        else:
            self.k = jnp.zeros(shape, self.dtype)
            self.v = jnp.zeros(shape, self.dtype)

    # -- allocator (host-side) --------------------------------------------

    @property
    def num_free(self):
        return len(self._free)

    @property
    def tokens_capacity(self):
        return self.num_free * self.page_size

    def allocate(self, n):
        """Pop n pages from the free list, or None when fewer remain
        (all-or-nothing: a partial grab would deadlock admission)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        if n == 0:
            return []
        pages, self._free = self._free[-n:][::-1], self._free[:-n]
        return pages

    def free(self, pages):
        for p in pages:
            p = int(p)
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"page {p} is not an allocatable id")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(int(p) for p in pages)

    def bytes_per_token(self):
        """K + V bytes of cache one token occupies across all layers."""
        itemsize = jnp.dtype(self.dtype).itemsize
        return 2 * self.num_layers * self.num_heads * self.head_dim * \
            itemsize
