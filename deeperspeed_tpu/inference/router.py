"""SLO-aware front-end router over a set of serving pools.

A disaggregated deployment (docs/inference.md "Disaggregated serving")
has several engines a client could submit to — prefill-role pools (and
unified ones, in a mixed fleet). `ServeRouter` is the single front
door: it scores every admitting pool by the saturation gauges the
engines already export (queue depth, page-pool utilization, measured
TTFT EMA) and routes each request to the weighted least-loaded pool.
The weights are the validated ``inference.router`` config sub-block
(`runtime.config._parse_inference_router`); absent, the defaults in
`runtime.constants` apply.

Shedding stays TYPED end to end: each pool's own admission controller
raises `RequestRejected` with a drain-rate retry-after hint, and when
EVERY candidate pool sheds, the router re-raises one `RequestRejected`
carrying the SMALLEST hint across pools — the soonest any pool expects
room. A client that honors it comes back exactly when capacity does.

Scale-down is a graceful drain: `drain(name)` removes the pool from
rotation first (no new requests can race in), then runs the engine's
own `drain()` — in-flight sequences finish or fail typed, never
silently.

Everything here is advisory-observable: `serve_stats()` records the
``Serve/router/*`` gauge families (routed/shed counters, per-pool load
scores, the cross-pool handoff p50, and an ``advise_scale_up`` bit that
flips when every routable pool's page pool sits above
``router.scale_up_util``) through the attached monitor, so a fleet
autoscaler can act on the scrape without any new plumbing.
"""

from ..runtime import constants as c
from ..utils.logging import logger
from .admission import RequestRejected
from .metrics import (ROUTER_ADVISE_SCALE_UP, ROUTER_HANDOFF_MS,
                      ROUTER_POOL_LOAD, ROUTER_ROUTED, ROUTER_SHED)

_ROUTER_DEFAULTS = {
    c.INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT:
        c.INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT_DEFAULT,
    c.INFERENCE_ROUTER_POOL_UTIL_WEIGHT:
        c.INFERENCE_ROUTER_POOL_UTIL_WEIGHT_DEFAULT,
    c.INFERENCE_ROUTER_TTFT_WEIGHT:
        c.INFERENCE_ROUTER_TTFT_WEIGHT_DEFAULT,
    c.INFERENCE_ROUTER_SCALE_UP_UTIL:
        c.INFERENCE_ROUTER_SCALE_UP_UTIL_DEFAULT,
}


class ServeRouter:
    """Weighted least-load routing over named serving pools.

    ``pools`` maps pool name -> `InferenceEngine`. Only admitting
    roles route (``prefill`` / ``unified``); a decode-role engine may
    be passed for observability but never receives a submit. ``config``
    is the validated ``inference.router`` params dict; None picks up
    the first pool's own parsed ``inference.router`` block (engines
    carry it as ``router_params``), falling back to the defaults."""

    def __init__(self, pools, config=None, monitor=None):
        if not pools:
            raise ValueError("ServeRouter needs at least one pool")
        self.pools = dict(pools)
        self.monitor = monitor
        if config is None:
            config = next(
                (eng.router_params for eng in self.pools.values()
                 if getattr(eng, "router_params", None)), None)
        params = dict(_ROUTER_DEFAULTS)
        if config:
            params.update(config)
        self.queue_depth_weight = \
            params[c.INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT]
        self.pool_util_weight = params[c.INFERENCE_ROUTER_POOL_UTIL_WEIGHT]
        self.ttft_weight = params[c.INFERENCE_ROUTER_TTFT_WEIGHT]
        self.scale_up_util = params[c.INFERENCE_ROUTER_SCALE_UP_UTIL]
        self._draining = set()
        self.stats = {"routed": 0, "shed": 0, "drained_pools": 0}
        # per-pool routed counts (serve_stats exports them as one
        # gauge per pool)
        self.routed_by_pool = {name: 0 for name in self.pools}

    # -- load scoring ------------------------------------------------------

    @staticmethod
    def _pool_gauges(engine):
        """(queue_depth, page_pool_util, ttft_ema_ms) read live off the
        engine — the same saturation signals its admission controller
        sheds on."""
        queue_depth = (len(engine.scheduler.waiting) +
                       len(engine.scheduler.quarantined))
        usable = max(engine.cache.num_pages - 1, 1)
        util = 1.0 - engine.cache.num_free / usable
        ttft_ema = 0.0
        if engine.admission is not None and \
                engine.admission.ttft_ema_ms is not None:
            ttft_ema = engine.admission.ttft_ema_ms
        return queue_depth, util, ttft_ema

    def load_score(self, name):
        """The weighted load this router routes by (lower = preferred)."""
        queue_depth, util, ttft_ema = self._pool_gauges(self.pools[name])
        return (self.queue_depth_weight * queue_depth +
                self.pool_util_weight * util +
                self.ttft_weight * ttft_ema)

    def routable_pools(self):
        """Names of pools a submit may target, best-scored first:
        admitting roles only, draining pools excluded."""
        names = [name for name, eng in self.pools.items()
                 if name not in self._draining and eng.role != "decode"]
        return sorted(names, key=self.load_score)

    # -- routing -----------------------------------------------------------

    def submit(self, prompt, max_new_tokens, **kwargs):
        """Route one request to the least-loaded admitting pool;
        returns ``(pool_name, request_id)``. Pools that shed are tried
        in load order; when ALL shed, re-raises a `RequestRejected`
        carrying the smallest retry-after hint across them."""
        candidates = self.routable_pools()
        if not candidates:
            raise RuntimeError(
                "ServeRouter has no routable pools (all draining or "
                "decode-role)")
        rejections = []
        for name in candidates:
            try:
                rid = self.pools[name].submit(prompt, max_new_tokens,
                                              **kwargs)
            except RequestRejected as e:
                rejections.append(e)
                continue
            self.stats["routed"] += 1
            self.routed_by_pool[name] += 1
            return name, rid
        self.stats["shed"] += 1
        soonest = min(rejections, key=lambda e: e.retry_after_s)
        raise RequestRejected(
            f"all {len(candidates)} routable pool(s) shed the request "
            f"(soonest retry-after {soonest.retry_after_s:.2f}s): "
            f"{soonest}", retry_after_s=soonest.retry_after_s,
            reason=soonest.reason, request=soonest.request)

    # -- scale-down --------------------------------------------------------

    def drain(self, name):
        """Scale a pool out: remove it from rotation FIRST (a racing
        submit cannot land on it), then run the engine's graceful
        drain — in-flight work finishes or fails typed. Returns the
        engine's drain summary; the pool stays in `pools` for
        observability but never routes again."""
        if name not in self.pools:
            raise KeyError(f"unknown pool {name!r}")
        self._draining.add(name)
        summary = self.pools[name].drain()
        self.stats["drained_pools"] += 1
        logger.info(f"router: pool {name!r} drained out of rotation: "
                    f"{summary}")
        return summary

    # -- convenience driving ----------------------------------------------

    @property
    def has_work(self):
        return any(eng.scheduler.has_work or eng._handoff_outbox or
                   eng._pending_handoff
                   for name, eng in self.pools.items()
                   if name not in self._draining)

    def step(self):
        """One step of every non-drained pool (single-host driving:
        tests and the bench run prefill and decode pools in one
        process)."""
        for name, eng in self.pools.items():
            if name not in self._draining:
                eng.step()

    def pop_finished(self):
        """Finished requests across every pool (drained ones included —
        their last results must not strand)."""
        out = []
        for eng in self.pools.values():
            out.extend(eng.scheduler.pop_finished())
        return out

    # -- observability -----------------------------------------------------

    def serve_stats(self):
        """Router gauges, recorded as ``Serve/router/*`` monitor
        scalars when a monitor is attached: routed/shed totals,
        per-pool load scores, the cross-pool handoff p50 (merged over
        every pool's handoff histogram), and the advisory scale-up
        bit."""
        out = dict(self.stats)
        loads = {name: self.load_score(name) for name in self.pools}
        out["pool_loads"] = loads
        for name, count in self.routed_by_pool.items():
            out[f"routed_{name}"] = count
        # merge the per-pool handoff distributions: the bucket ladders
        # are shared, so bucket-wise sums ARE the merged histogram
        merged = None
        for eng in self.pools.values():
            hist = eng.request_metrics.handoff
            if hist.count == 0:
                continue
            if merged is None:
                from ..runtime.exporters import Histogram
                merged = Histogram(hist.edges)
            merged.counts = [a + b for a, b in zip(merged.counts,
                                                   hist.counts)]
            merged.inf_count += hist.inf_count
            merged.total += hist.total
            merged.count += hist.count
        if merged is not None:
            out["handoff_p50_ms"] = merged.percentile(0.5)
            out["handoff_p99_ms"] = merged.percentile(0.99)
        routable = [n for n in self.pools if n not in self._draining and
                    self.pools[n].role != "decode"]
        saturated = bool(routable) and all(
            self._pool_gauges(self.pools[n])[1] > self.scale_up_util
            for n in routable)
        out["advise_scale_up"] = 1.0 if saturated else 0.0
        if self.monitor is not None:
            scalars = {ROUTER_ROUTED: float(out["routed"]),
                       ROUTER_SHED: float(out["shed"]),
                       ROUTER_ADVISE_SCALE_UP: out["advise_scale_up"]}
            if "handoff_p50_ms" in out:
                scalars[ROUTER_HANDOFF_MS] = float(out["handoff_p50_ms"])
            for name, load in loads.items():
                scalars[f"{ROUTER_POOL_LOAD}/{name}"] = float(load)
            total = sum(e.stats["prefill_tokens"] +
                        e.stats["decode_tokens"]
                        for e in self.pools.values())
            self.monitor.record(total, scalars)
        return out
