"""SLO-aware admission control + load shedding for the serving engine.

The PR 8 scheduler's only overload behavior was FIFO back-pressure: the
waiting deque grew without bound, every queued request eventually ran,
and a client could not tell "30s queueing delay ahead" from "healthy".
Production TPU serving treats tail-latency SLOs under bursty load as
the headline metric, which needs the opposite discipline: **shed early,
shed the right requests, and tell the client when to come back**.

`AdmissionController` gates `InferenceEngine.submit()` on the three
saturation signals the engine already exports per step (PR 10):

- **queue depth** — the bounded admission queue: past
  ``max_queue_depth`` every class sheds (an unbounded queue converts
  overload into unbounded latency, the worst possible SLO response);
- **page-pool utilization** — past ``shed_page_pool_util`` the pool is
  one burst away from eviction thrash, so ``batch``-priority requests
  shed while ``interactive`` ones still admit (the priority classes'
  whole point);
- **TTFT EMA** — an exponential moving average of measured
  time-to-first-token. Past ``shed_ttft_ema_ms`` batch requests shed;
  independently, a request carrying its own ``ttft_slo_ms`` is shed
  (any class) when the measured EMA already exceeds what it asks for —
  admitting it would burn compute on a guaranteed SLO miss. Both EMA
  signals require a LIVE backlog (``queue_depth > 0``): the EMA only
  refreshes on admitted requests' first tokens, so a stale high EMA on
  an idle server must not shed traffic forever.

Shed requests surface as a typed `RequestRejected` carrying the
terminal ``shed`` status, the triggering reason, and a **retry-after
hint computed from the measured drain rate** (an EMA of request
completions per second): ``excess backlog / drain rate``, clamped to
``[0.05s, retry_after_cap_s]``. Clients that honor the hint arrive
when the queue has actually drained instead of dog-piling.

The typed request-terminal errors live here too (`DeadlineExceeded`,
`RequestFailed`, `DrainAborted`): every request the engine accepts
reaches exactly one terminal status — ``ok`` / ``shed`` /
``deadline_exceeded`` / ``failed`` — and the non-``ok`` ones carry one
of these exceptions in ``Request.error`` (docs/inference.md lists the
taxonomy).
"""

import time

# priority classes, high to low. `interactive` is user-facing traffic
# (shed last, evicted last); `batch` is offline/bulk traffic (shed
# first under overload, evicted first under page pressure).
PRIORITIES = ("interactive", "batch")
PRIORITY_RANK = {name: i for i, name in enumerate(PRIORITIES)}

# terminal request statuses — every accepted request reaches exactly
# one (scheduler enforces single assignment); shed requests never enter
# the scheduler and carry STATUS_SHED on the RequestRejected error
STATUS_OK = "ok"
STATUS_SHED = "shed"
STATUS_DEADLINE = "deadline_exceeded"
STATUS_FAILED = "failed"
REQUEST_STATUSES = (STATUS_OK, STATUS_SHED, STATUS_DEADLINE,
                    STATUS_FAILED)


class RequestRejected(RuntimeError):
    """Typed shed verdict from admission control. ``retry_after_s`` is
    the drain-rate-derived back-off hint; ``reason`` is one of
    ``queue_full`` / ``overload`` / ``slo_unattainable``."""

    def __init__(self, message, retry_after_s, reason, request=None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.reason = str(reason)
        self.request = request


class DeadlineExceeded(RuntimeError):
    """A request's ``deadline_ms`` elapsed before it finished — it is
    terminated with status ``deadline_exceeded`` instead of consuming
    further decode cadence (the client has already given up)."""


class RequestFailed(RuntimeError):
    """Terminal step-failure verdict: the request failed
    ``retry.max_attempts`` consecutive prefill/decode steps and is
    poisoned permanently (the serving mirror of PR 9's poison-step
    detector). ``last_error`` holds the final underlying exception."""

    def __init__(self, message, last_error=None, attempts=0):
        super().__init__(message)
        self.last_error = last_error
        self.attempts = int(attempts)


class DrainAborted(RequestFailed):
    """The graceful-drain deadline elapsed with this request still in
    flight: it is failed (typed, flushed to metrics) rather than
    silently abandoned, so the client can tell drain from crash."""


def validate_priority(priority):
    """Priority-class name -> rank; typos raise with the choices listed
    (the same strictness the config parser applies)."""
    if priority not in PRIORITY_RANK:
        raise ValueError(
            f"unknown priority class {priority!r}; choices: "
            f"{list(PRIORITIES)}")
    return PRIORITY_RANK[priority]


class AdmissionController:
    """The submit-time gate. Host-side and O(1) per decision — the
    serving hot loop never waits on it.

    ``params`` is the validated ``inference.admission`` dict
    (`runtime.config.parse_inference_block`). Signals are pushed by the
    engine: `observe_ttft` after each first token, `note_finished` at
    each step end (feeds the drain-rate EMA the retry-after hint is
    computed from)."""

    def __init__(self, params, clock=time.perf_counter):
        self.max_queue_depth = int(params["max_queue_depth"])
        self.shed_page_pool_util = float(params["shed_page_pool_util"])
        self.shed_ttft_ema_ms = params["shed_ttft_ema_ms"]
        self.ttft_ema_beta = float(params["ttft_ema_beta"])
        self.retry_after_cap_s = float(params["retry_after_cap_s"])
        self._clock = clock

        self._ttft_ema_ms = None
        self._drain_rate = None       # finished requests / second (EMA)
        self._last_finish_at = None
        self.shed_counts = {"queue_full": 0, "overload": 0,
                            "slo_unattainable": 0}

    # -- signal intake -----------------------------------------------------

    @property
    def ttft_ema_ms(self):
        return self._ttft_ema_ms

    @property
    def drain_rate(self):
        """Measured request completions per second (None pre-warmup)."""
        return self._drain_rate

    def observe_ttft(self, ms):
        ms = float(ms)
        if self._ttft_ema_ms is None:
            self._ttft_ema_ms = ms
        else:
            b = self.ttft_ema_beta
            self._ttft_ema_ms = b * self._ttft_ema_ms + (1.0 - b) * ms

    def note_finished(self, n, now=None):
        """n requests reached a terminal status this step — update the
        drain-rate EMA from the inter-completion interval."""
        if n <= 0:
            return
        now = self._clock() if now is None else now
        if self._last_finish_at is not None:
            dt = now - self._last_finish_at
            if dt > 0:
                rate = n / dt
                if self._drain_rate is None:
                    self._drain_rate = rate
                else:
                    b = self.ttft_ema_beta
                    self._drain_rate = b * self._drain_rate + \
                        (1.0 - b) * rate
        self._last_finish_at = now

    # -- the verdict -------------------------------------------------------

    def retry_after_s(self, queue_depth):
        """Back-off hint from the measured drain rate: how long until
        the current backlog (plus the rejected request) has drained.
        Conservative 1s default before any completion was measured."""
        if not self._drain_rate or self._drain_rate <= 0:
            return 1.0
        hint = (queue_depth + 1) / self._drain_rate
        return min(max(hint, 0.05), self.retry_after_cap_s)

    def admit(self, request, queue_depth, page_pool_util):
        """Admit or shed one request. Returns None on admit; raises
        `RequestRejected` (after stamping the request's terminal
        ``shed`` status) on shed."""
        reason = None
        # TTFT-EMA sheds require a LIVE backlog: the EMA only refreshes
        # when admitted requests deliver first tokens, so on an idle
        # server (empty queue) a stale high EMA from a past burst would
        # otherwise shed SLO-carrying traffic forever — with nothing
        # admitted, nothing could ever bring the EMA back down
        backlogged = queue_depth > 0
        if queue_depth >= self.max_queue_depth:
            reason = "queue_full"
            detail = (f"admission queue is full "
                      f"({queue_depth}/{self.max_queue_depth})")
        elif backlogged and request.ttft_slo_ms is not None and \
                self._ttft_ema_ms is not None and \
                self._ttft_ema_ms > request.ttft_slo_ms:
            # any class: the measured TTFT already misses what this
            # request asks for — admitting it burns compute on a
            # guaranteed SLO violation
            reason = "slo_unattainable"
            detail = (f"measured TTFT EMA {self._ttft_ema_ms:.0f}ms "
                      f"exceeds the request's ttft_slo_ms "
                      f"{request.ttft_slo_ms:.0f}ms")
        elif PRIORITY_RANK.get(request.priority, 0) > 0:
            # batch-class traffic sheds on the soft overload signals
            # interactive traffic rides out
            if page_pool_util >= self.shed_page_pool_util:
                reason = "overload"
                detail = (f"page pool {page_pool_util:.0%} utilized "
                          f"(>= shed_page_pool_util "
                          f"{self.shed_page_pool_util:.0%})")
            elif backlogged and self.shed_ttft_ema_ms is not None and \
                    self._ttft_ema_ms is not None and \
                    self._ttft_ema_ms > self.shed_ttft_ema_ms:
                reason = "overload"
                detail = (f"TTFT EMA {self._ttft_ema_ms:.0f}ms past the "
                          f"shed threshold {self.shed_ttft_ema_ms:.0f}ms")
        if reason is None:
            return None
        self.shed_counts[reason] += 1
        request.status = STATUS_SHED
        hint = self.retry_after_s(queue_depth)
        err = RequestRejected(
            f"request shed ({reason}): {detail}; retry after "
            f"{hint:.2f}s", retry_after_s=hint, reason=reason,
            request=request)
        request.error = err
        raise err
