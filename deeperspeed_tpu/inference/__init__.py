"""Serving subsystem: continuous batching, paged KV cache, and the
Pallas paged decode-attention kernel (`docs/inference.md`).

- `InferenceEngine` — the serving loop: bucketed prefill/decode split
  at fixed compiled shapes, params-only checkpoint loading, telemetry.
- `PagedKVCache` — the preallocated, mesh-sharded page pool + its
  host-side refcounting allocator.
- `PrefixCache` — the radix-style prefix registry over the page pool
  (cross-request KV reuse; docs/inference.md "Prefix/radix cache").
- `ContinuousBatchingScheduler` / `Request` — per-step admission and
  eviction under a token + page budget.
- `AdmissionController` + the typed request-terminal errors
  (`RequestRejected` / `DeadlineExceeded` / `RequestFailed` /
  `DrainAborted`) — the SLO-aware robustness layer
  (docs/inference.md "Serving under failure").
- `HandoffChannel` / `HandoffRejected` + `ServeRouter` — disaggregated
  prefill/decode serving: the cross-pool KV-page handoff wire and the
  SLO-aware front-end router (docs/inference.md "Disaggregated
  serving").
"""

from .admission import (AdmissionController, DeadlineExceeded,
                        DrainAborted, PRIORITIES, RequestFailed,
                        RequestRejected, REQUEST_STATUSES)
from .engine import InferenceEngine
from .handoff import HandoffChannel, HandoffRejected
from .kv_cache import PagedKVCache, PrefixCache, pages_for_tokens
from .router import ServeRouter
from .scheduler import ContinuousBatchingScheduler, Request, StepPlan

__all__ = ["InferenceEngine", "PagedKVCache", "PrefixCache",
           "pages_for_tokens",
           "ContinuousBatchingScheduler", "Request", "StepPlan",
           "AdmissionController", "RequestRejected", "DeadlineExceeded",
           "RequestFailed", "DrainAborted", "PRIORITIES",
           "REQUEST_STATUSES",
           "HandoffChannel", "HandoffRejected", "ServeRouter"]
