"""Cross-pool KV-page handoff for disaggregated prefill/decode serving.

A prefill-role engine (docs/inference.md "Disaggregated prefill/
decode") runs admission + prefill only; when a request's prefill
completes, its KV pages leave the prefill pool and the request
continues mid-stream on a decode-role engine. The pages travel over the
same coordination-service KV transport the PR 9/10 heartbeats and fleet
summaries ride (`elasticity.heartbeat.InMemoryTransport` /
`CoordinationTransport`), so a two-pool split is single-host drivable
in tests and cross-host in production with zero new infrastructure.

Wire format (`encode_pages` / `decode_pages`): page rows are gathered
host-side from the ``[L, P, H, page_size, D]`` pools into an
``[L, n, H, page_size, D]`` block and shipped as base64 raw bytes —
bit-exact round-trips by construction, pinned by test for bf16 AND int8
pools. Int8 pages are SELF-DESCRIBING: the per-page bf16 scale rows
``[L, n, H, page_size]`` travel in the same payload, so an installed
page dequantizes identically on the decode pool. Page 0 (the reserved
trash page) never ships — `encode_pages` refuses it loudly.

Offer/ack protocol (`HandoffChannel`): one KV slot per offer, keyed
``ds_serve:offer:<dst>:<src>:<uid>``. The prefill side publishes the
offer (state ``offer``, pages + request metadata); the decode side
installs and OVERWRITES the slot with a small ack tombstone (state
``accepted`` / ``rejected``) — the page bytes never outlive their one
trip. The prefill side frees its local pages on ``accepted``, requeues
the request (eviction semantics: full-context re-prefill, then a fresh
offer) on ``rejected``, and withdraws + requeues offers that outlive
``handoff_timeout_s``. Consumed slots are retired via the transports'
best-effort ``discard`` so a long-lived split cannot grow the store
without bound. The timeout path trades a fencing lease for simplicity:
an ack that lands after the withdrawal is dropped as stale, but a
decode pool that installed in exactly that window generates a
duplicate — set the timeout well above the transport RTT.
"""

import base64

import numpy as np

import jax.numpy as jnp

from .kv_cache import QuantizedPages

# transport key namespaces (shared store with the heartbeats — the
# prefix keeps read_all filtering cheap and collision-free). ":" as
# the segment separator, NOT "/": CoordinationTransport.read_all
# collapses keys to their first "/" segment (the heartbeat per-peer
# convention), so channel keys must be single-segment under it
_POOL_PREFIX = "ds_serve:pool"
_OFFER_PREFIX = "ds_serve:offer"

OFFER = "offer"
ACCEPTED = "accepted"
REJECTED = "rejected"
WITHDRAWN = "withdrawn"


class HandoffRejected(Exception):
    """The decode pool could not install an offered request. ``reason``
    is machine-readable (``busy`` / ``pool_full`` / ``geometry`` /
    ``draining``) and rides the ack back to the prefill side."""

    def __init__(self, message, reason):
        super().__init__(message)
        self.reason = reason


def _b64(arr):
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode(
        "ascii")


def _unb64(text, dtype, shape):
    buf = base64.b64decode(text.encode("ascii"))
    return np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape)


def encode_pages(cache, page_ids):
    """Serialize page rows of a `PagedKVCache` into a JSON-safe dict.

    Gathers ``[L, n, H, page_size, D]`` blocks from the K and V pools
    (plus the per-page scale rows for int8 pools) and base64-encodes
    the raw bytes — the round-trip is bit-exact. Page 0 is the
    reserved trash page and must never ship."""
    ids = [int(p) for p in page_ids]
    if any(p <= 0 or p >= cache.num_pages for p in ids):
        raise ValueError(
            f"cannot encode page ids {ids}: page 0 is the reserved "
            f"trash page and ids must sit inside the pool")
    idx = jnp.asarray(ids, jnp.int32)
    out = {
        "n": len(ids),
        "kv_dtype": str(jnp.dtype(cache.dtype)),
        "shape": [cache.num_layers, cache.num_heads, cache.page_size,
                  cache.head_dim],
    }
    for name, pool in (("k", cache.k), ("v", cache.v)):
        if isinstance(pool, QuantizedPages):
            out[name] = _b64(np.asarray(pool.data[:, idx]))
            out[f"{name}_scale"] = _b64(np.asarray(pool.scale[:, idx]))
        else:
            out[name] = _b64(np.asarray(pool[:, idx]))
    return out


def decode_pages(payload):
    """Inverse of `encode_pages`: returns ``(k, v, k_scale, v_scale)``
    numpy blocks (scales None for non-int8 payloads)."""
    L, H, ps, D = payload["shape"]
    n = payload["n"]
    dtype = jnp.dtype(payload["kv_dtype"])
    k = _unb64(payload["k"], dtype, (L, n, H, ps, D))
    v = _unb64(payload["v"], dtype, (L, n, H, ps, D))
    k_scale = v_scale = None
    if "k_scale" in payload:
        k_scale = _unb64(payload["k_scale"], jnp.bfloat16, (L, n, H, ps))
        v_scale = _unb64(payload["v_scale"], jnp.bfloat16, (L, n, H, ps))
    return k, v, k_scale, v_scale


def write_pages(cache, page_ids, payload, skip=0):
    """Install decoded page rows into ``page_ids`` of ``cache`` (rows
    ``skip..n`` of the payload — a prefix-cache dedupe hit skips the
    rows whose pages the registry already holds). One batched scatter
    per pool leaf; the functional pools are rebound on the cache like
    every compiled-call rebind, so no new compiled shapes appear."""
    if not page_ids:
        return
    if payload["kv_dtype"] != str(jnp.dtype(cache.dtype)):
        raise HandoffRejected(
            "page payload precision does not match the pool "
            f"(payload {payload['kv_dtype']}, pool "
            f"{jnp.dtype(cache.dtype)})", reason="geometry")
    k, v, k_scale, v_scale = decode_pages(payload)
    idx = jnp.asarray([int(p) for p in page_ids], jnp.int32)
    quant = isinstance(cache.k, QuantizedPages)
    if quant:
        cache.k = QuantizedPages(
            cache.k.data.at[:, idx].set(jnp.asarray(k[:, skip:])),
            cache.k.scale.at[:, idx].set(jnp.asarray(k_scale[:, skip:])))
        cache.v = QuantizedPages(
            cache.v.data.at[:, idx].set(jnp.asarray(v[:, skip:])),
            cache.v.scale.at[:, idx].set(jnp.asarray(v_scale[:, skip:])))
    else:
        cache.k = cache.k.at[:, idx].set(jnp.asarray(k[:, skip:]))
        cache.v = cache.v.at[:, idx].set(jnp.asarray(v[:, skip:]))


def check_geometry(cache, payload):
    """Reject (typed) a payload whose page geometry or pool precision
    cannot land in ``cache`` — a decode pool configured with a
    different page_size/head layout must bounce the offer back, not
    corrupt its pool."""
    want = [cache.num_layers, cache.num_heads, cache.page_size,
            cache.head_dim]
    if list(payload["shape"]) != want:
        raise HandoffRejected(
            f"page geometry {payload['shape']} does not match the "
            f"decode pool {want}", reason="geometry")
    if payload["kv_dtype"] != str(jnp.dtype(cache.dtype)):
        raise HandoffRejected(
            f"page precision {payload['kv_dtype']!r} does not match "
            f"the decode pool {jnp.dtype(cache.dtype)}",
            reason="geometry")


class HandoffChannel:
    """The offer/ack wire over one KV transport (module docstring).

    All payloads carry a per-channel monotonic ``serial`` — the
    CoordinationTransport append-only fallback keys on it, and pool
    announcements resolve freshest-wins through it."""

    def __init__(self, transport, pool_id):
        self.transport = transport
        self.pool_id = str(pool_id)
        self._serial = 0

    def _next_serial(self):
        self._serial += 1
        return self._serial

    # -- pool discovery ---------------------------------------------------

    def announce(self, role, load):
        """Publish this pool's role + load gauge (one overwritten slot
        per pool) — the prefill side's weighted least-load dst pick and
        the router's pool map both read these."""
        self.transport.publish(f"{_POOL_PREFIX}:{self.pool_id}", {
            "serial": self._next_serial(), "pool_id": self.pool_id,
            "role": str(role), "load": float(load)})

    def pools(self, role=None):
        """{pool_id: announcement} of every announced pool (filtered by
        role when given)."""
        out = {}
        for key, payload in self.transport.read_all().items():
            if not str(key).startswith(_POOL_PREFIX + ":"):
                continue
            if role is not None and payload.get("role") != role:
                continue
            out[payload.get("pool_id", key)] = payload
        return out

    def choose_decode_pool(self):
        """Least-loaded announced decode pool, or None."""
        pools = self.pools(role="decode")
        if not pools:
            return None
        return min(pools, key=lambda p: pools[p].get("load", 0.0))

    # -- offers / acks ----------------------------------------------------

    def offer(self, dst, uid, payload):
        """Publish one offer to pool ``dst``; returns the slot key the
        ack comes back on."""
        key = f"{_OFFER_PREFIX}:{dst}:{self.pool_id}:{uid}"
        body = dict(payload)
        body["state"] = OFFER
        body["serial"] = self._next_serial()
        self.transport.publish(key, body)
        return key

    def poll_offers(self):
        """Un-acked offers addressed to this pool: [(key, payload)]."""
        mine = f"{_OFFER_PREFIX}:{self.pool_id}:"
        out = []
        for key, payload in self.transport.read_all().items():
            if str(key).startswith(mine) and \
                    payload.get("state") == OFFER:
                out.append((str(key), payload))
        out.sort(key=lambda kv: kv[1].get("serial", 0))
        return out

    def ack(self, key, ok, reason=None):
        """Overwrite an offer slot with its ack tombstone — the page
        bytes are gone from the store the moment the verdict lands."""
        self.transport.publish(key, {
            "state": ACCEPTED if ok else REJECTED,
            "reason": reason, "serial": self._next_serial()})

    def withdraw(self, key):
        """Overwrite a timed-out offer so a late decode-side read skips
        it instead of installing a request the prefill side already
        requeued."""
        self.transport.publish(key, {
            "state": WITHDRAWN, "serial": self._next_serial()})

    def poll_acks(self):
        """Acks for offers THIS pool published: [(key, uid, payload)]."""
        out = []
        for key, payload in self.transport.read_all().items():
            key = str(key)
            if not key.startswith(_OFFER_PREFIX + ":"):
                continue
            if payload.get("state") not in (ACCEPTED, REJECTED):
                continue
            parts = key[len(_OFFER_PREFIX) + 1:].split(":", 2)
            if len(parts) != 3 or parts[1] != self.pool_id:
                continue
            out.append((key, parts[2], payload))
        return out

    def retire(self, key):
        """Best-effort removal of a consumed slot (transports without
        delete leave the small tombstone behind — bounded growth)."""
        discard = getattr(self.transport, "discard", None)
        if discard is not None:
            discard(str(key))
