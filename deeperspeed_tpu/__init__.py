"""DeeperSpeed-TPU: a TPU-native distributed training framework with the
capability surface of DeeperSpeed (EleutherAI's DeepSpeed v0.3.15 fork).

The public API mirrors the reference (`deepspeed/__init__.py`):
``initialize()`` returns ``(engine, optimizer, dataloader, lr_scheduler)``;
JSON configs written for the reference parse unmodified. The machinery
underneath is JAX/XLA/pjit/Pallas over a `jax.sharding.Mesh`.
"""

import argparse

from . import moe, ops  # noqa: F401
from .elasticity import compute_elastic_config, elasticity_enabled
from .parallel.mesh import PipelineParallelGrid
from .parallel.topology import (PipeDataParallelTopology,
                                PipeModelDataParallelTopology,
                                ProcessTopology)
from .runtime import zero  # noqa: F401
from .inference import InferenceEngine
from .runtime.config import DeepSpeedConfig
from .runtime.engine import DeepSpeedEngine
from .runtime.lr_schedules import add_tuning_arguments
from .runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec
from .runtime.pipe.engine import PipelineEngine
from .runtime.sentinel import TrainingHealthError
from .utils.distributed import init_distributed
from .utils.logging import log_dist, logger
from .version import __version__

# git-style version info for parity with deepspeed.git_version_info
git_hash = None
git_branch = None


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None,
               dist_init_required=None, collate_fn=None, config=None,
               config_params=None, mesh=None, rng=None):
    """Initialize the DeepSpeed engine (reference `__init__.py:52-145`).

    Arguments match the reference; `model` is a pure
    ``loss_fn(params, batch, rng) -> loss`` (or an object exposing
    ``loss_fn``/``init_params``) instead of an ``nn.Module``, and
    ``model_parameters`` is the parameter pytree. A ``PipelineModule``
    model selects the ``PipelineEngine``.

    Returns: tuple of ``(engine, optimizer, training_dataloader,
    lr_scheduler)``.
    """
    log_dist(f"DeeperSpeed-TPU info: version={__version__}", ranks=[0])

    if dist_init_required is None or dist_init_required:
        init_distributed()

    if isinstance(model, PipelineModule):
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=model.mpu() if mpu is None else mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config=config,
                                config_params=config_params,
                                mesh=mesh,
                                rng=rng)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config=config,
                                 config_params=config_params,
                                 mesh=mesh,
                                 rng=rng)

    return (engine, engine.optimizer, engine.training_dataloader,
            engine.lr_scheduler)


def _add_core_arguments(parser):
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse.SUPPRESS)  # deprecated spelling
    group.add_argument("--deepscale_config", default=None, type=str,
                       help=argparse.SUPPRESS)
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Discover rank/world from MPI")
    return parser


def add_config_arguments(parser):
    """Add DeepSpeed's argparse flags (reference `__init__.py:199`)."""
    return _add_core_arguments(parser)
