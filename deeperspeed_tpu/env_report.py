"""`ds_report` environment report (reference: `deepspeed/env_report.py`).

Reports the op/kernel availability matrix (Pallas kernels replace the JIT
CUDA op builders) and the JAX/TPU environment instead of torch/CUDA.
"""

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[YES]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"
OKAY = f"{GREEN}[OKAY]{END}"


def op_report():
    """Kernel/feature availability matrix."""
    from .ops.compat import ALL_OPS

    max_dots = 23
    print("-" * 64)
    print("DeeperSpeed-TPU op/kernel report")
    print("-" * 64)
    print("op name", "." * max_dots, "available")
    print("-" * 64)
    rows = []
    for name, check in ALL_OPS.items():
        try:
            ok = check()
        except Exception:
            ok = False
        status = OKAY if ok else FAIL
        print(name, "." * (max_dots + 8 - len(name)), status)
        rows.append((name, ok))
    print("-" * 64)
    return rows


def env_fingerprint():
    """Machine-readable environment identity: versions, device kind,
    process count/index, and topology. This is what `ds_report --json`
    prints and what the fleet trace collector embeds in merged-capture
    metadata (`runtime/fleet.py`) — WHICH jax/jaxlib/device produced a
    trace matters when comparing lanes across hosts."""
    import jax

    import numpy as np

    from .version import __version__

    info = {
        "deeperspeed_tpu": __version__,
        "jax": jax.__version__,
        "numpy": np.__version__,
    }
    try:
        import jaxlib
        info["jaxlib"] = jaxlib.__version__
    except Exception:  # noqa: BLE001 - bundled builds
        info["jaxlib"] = getattr(getattr(jax, "lib", None), "__version__",
                                 None)
    try:
        devices = jax.devices()
        info.update({
            "backend": jax.default_backend(),
            "device_count": len(devices),
            "local_device_count": len(jax.local_devices()),
            "device_kind": (getattr(devices[0], "device_kind", "unknown")
                            if devices else "none"),
            "process_count": jax.process_count(),
            "process_index": jax.process_index(),
            "topology": {
                "platforms": sorted({getattr(d, "platform", "unknown")
                                     for d in devices}),
                "devices_per_process": (len(devices)
                                        // max(jax.process_count(), 1)),
            },
        })
    except RuntimeError as e:  # backend not initializable here
        info["backend_error"] = str(e)
    try:
        import flax
        info["flax"] = flax.__version__
    except ImportError:
        pass
    # which static-analysis invariant set this checkout was gated on.
    # tools/ is repo-local, not installed with the package — and "tools"
    # is a common top-level name, so a foreign package on sys.path may
    # sit there and raise anything at import: never let it break
    # ds_report itself.
    try:
        from tools.dslint import RULESET_VERSION
        info["dslint_ruleset"] = RULESET_VERSION
    except Exception:  # noqa: BLE001 - absent or foreign tools package
        info["dslint_ruleset"] = None
    # newest persisted schedule plan, if any — ties a captured trace /
    # CI run to the exact plan that shaped its schedule (docs/planner.md)
    try:
        from .planner import latest_plan_fingerprint
        info["plan_fingerprint"] = latest_plan_fingerprint()
    except Exception:  # noqa: BLE001 - unreadable plan cache
        info["plan_fingerprint"] = None
    return info


def json_report():
    """The full `ds_report --json` payload: environment fingerprint +
    op/kernel availability matrix."""
    from .ops.compat import ALL_OPS
    ops = {}
    for name, check in ALL_OPS.items():
        try:
            ops[name] = bool(check())
        except Exception:  # noqa: BLE001 - probe failure = unavailable
            ops[name] = False
    return {"env": env_fingerprint(), "ops": ops}


def debug_report():
    import jax

    import numpy as np

    from .version import __version__

    rows = [
        ("deeperspeed_tpu version", __version__),
        ("jax version", jax.__version__),
        ("numpy version", np.__version__),
    ]
    try:
        devices = jax.devices()
        rows += [
            ("default backend", jax.default_backend()),
            ("device count", len(devices)),
            ("device kind", getattr(devices[0], "device_kind", "unknown")
             if devices else "none"),
            ("process count", jax.process_count()),
        ]
    except RuntimeError as e:  # backend not initializable in this context
        rows.append(("device backend", f"unavailable ({e})"))
    try:
        import flax
        rows.append(("flax version", flax.__version__))
    except ImportError:
        pass
    print("-" * 64)
    print("DeeperSpeed-TPU general environment info:")
    for name, value in rows:
        print(f"{name} ................ {value}")
    print("-" * 64)
    return rows


def main(argv=None):
    import sys
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--json" in argv:
        # machine-readable mode: env fingerprint + op matrix, nothing
        # else on stdout (the fleet collector and CI parse this)
        import json
        print(json.dumps(json_report(), indent=2, default=str))
        return
    op_report()
    debug_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
