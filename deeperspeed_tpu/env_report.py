"""`ds_report` environment report (reference: `deepspeed/env_report.py`).

Reports the op/kernel availability matrix (Pallas kernels replace the JIT
CUDA op builders) and the JAX/TPU environment instead of torch/CUDA.
"""

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[YES]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"
OKAY = f"{GREEN}[OKAY]{END}"


def op_report():
    """Kernel/feature availability matrix."""
    from .ops.compat import ALL_OPS

    max_dots = 23
    print("-" * 64)
    print("DeeperSpeed-TPU op/kernel report")
    print("-" * 64)
    print("op name", "." * max_dots, "available")
    print("-" * 64)
    rows = []
    for name, check in ALL_OPS.items():
        try:
            ok = check()
        except Exception:
            ok = False
        status = OKAY if ok else FAIL
        print(name, "." * (max_dots + 8 - len(name)), status)
        rows.append((name, ok))
    print("-" * 64)
    return rows


def debug_report():
    import jax

    import numpy as np

    from .version import __version__

    rows = [
        ("deeperspeed_tpu version", __version__),
        ("jax version", jax.__version__),
        ("numpy version", np.__version__),
    ]
    try:
        devices = jax.devices()
        rows += [
            ("default backend", jax.default_backend()),
            ("device count", len(devices)),
            ("device kind", getattr(devices[0], "device_kind", "unknown")
             if devices else "none"),
            ("process count", jax.process_count()),
        ]
    except RuntimeError as e:  # backend not initializable in this context
        rows.append(("device backend", f"unavailable ({e})"))
    try:
        import flax
        rows.append(("flax version", flax.__version__))
    except ImportError:
        pass
    print("-" * 64)
    print("DeeperSpeed-TPU general environment info:")
    for name, value in rows:
        print(f"{name} ................ {value}")
    print("-" * 64)
    return rows


def main():
    op_report()
    debug_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
