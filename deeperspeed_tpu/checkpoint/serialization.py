"""Checkpoint serialization: pytree ↔ portable state dicts.

Files use torch's container format (torch is host-side only here) so the
on-disk layout matches the reference ecosystem's expectations
(`*_model_states.pt`, `*_optim_states.pt` — reference `engine.py:1764-1818`);
tensors are stored as numpy arrays inside. Falls back to pickle if torch is
unavailable.
"""

import pickle

import numpy as np

import jax

try:
    import torch
    _HAVE_TORCH = True
except Exception:  # pragma: no cover
    _HAVE_TORCH = False


def save_obj(obj, path, all_ranks=False):
    # Multi-process: every process computes the (collectively gathered)
    # state, but only process 0 touches the filesystem (reference
    # `engine.py` rank-0 save gating). Callers barrier afterwards.
    # all_ranks=True writes from EVERY process — for per-process shard
    # files (the reference's every-rank zero-shard write,
    # `engine.py:1810-1818`); the path must then be rank-unique.
    if not all_ranks and jax.process_index() != 0:
        return
    if _HAVE_TORCH:
        torch.save(obj, path)
    else:  # pragma: no cover
        with open(path, "wb") as f:
            pickle.dump(obj, f)


def load_obj(path):
    if _HAVE_TORCH:
        return torch.load(path, map_location="cpu", weights_only=False)
    with open(path, "rb") as f:  # pragma: no cover
        return pickle.load(f)


def _path_key(path):
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def to_host(leaf):
    """Leaf → numpy on THIS host. Multi-process arrays are not fully
    addressable locally — gather the global value over DCN first
    (checkpoint writers need whole arrays)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(
            multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def tree_to_state_dict(tree):
    """Flatten a pytree to {path: numpy array} + treedef pickle for exact
    structure restoration."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    arrays = {_path_key(path): to_host(leaf) for path, leaf in flat}
    return {"arrays": arrays, "treedef": pickle.dumps(treedef)}


def state_dict_to_tree(sd, like=None):
    """Rebuild the pytree. If `like` is given, values are matched to its
    structure by path (robust to treedef pickle incompatibilities)."""
    if like is not None:
        flat, treedef = jax.tree_util.tree_flatten_with_path(like)
        leaves = []
        for path, leaf in flat:
            key = _path_key(path)
            if key not in sd["arrays"]:
                raise KeyError(f"checkpoint missing parameter {key!r}")
            leaves.append(sd["arrays"][key])
        return jax.tree_util.tree_unflatten(treedef, leaves)
    treedef = pickle.loads(sd["treedef"])
    # tree_flatten_with_path ordering == tree_flatten ordering.
    keys = list(sd["arrays"].keys())
    return jax.tree_util.tree_unflatten(treedef,
                                        [sd["arrays"][k] for k in keys])


def shard_slice(array, num_parts, rank, dim):
    """GSPMD-convention shard: ceil-chunk along `dim` (last shard may be
    short)."""
    n = array.shape[dim]
    chunk = -(-n // num_parts)
    start = min(rank * chunk, n)
    stop = min(start + chunk, n)
    index = [slice(None)] * array.ndim
    index[dim] = slice(start, stop)
    return array[tuple(index)]


def unshard_concat(shards, dim):
    return np.concatenate(shards, axis=dim)
