"""Engine checkpoint save/load with the reference directory layout
(reference: `deepspeed/runtime/engine.py:1491-1818`).

Layout written:

    {save_dir}/{tag}/mp_rank_{mp:02d}_model_states.pt
    {save_dir}/{tag}/zero_pp_rank_{dp}_mp_rank_{mp:02d}_optim_states.pt
    {save_dir}/{tag}/manifest.json
    {save_dir}/latest

Model-states files hold module params + scheduler/counter state; when ZeRO
is enabled the fp32 masters + optimizer moments are written per-dp-rank as
GSPMD-convention slices along each leaf's sharded dim, and reassembled (and
re-placed with the *current* shardings) on load — which is exactly the
reference's elastic checkpointing: a job restarted at a different dp world
size merges the saved partitions and re-slices (`stage2.py:1825-1894`).

Saves are two-phase (snapshot-then-commit, see `manifest.py` for the
commit protocol): `snapshot_checkpoint` materializes every array on the
host — the only part that stalls training — and `write_and_commit` turns
the resulting payloads into a crash-consistent checkpoint directory. The
sync `save_checkpoint` runs both phases inline; `async_manager.
AsyncCheckpointManager` runs the commit in a background writer thread so
training overlaps the serialization + disk I/O. `load_checkpoint`
verifies the manifest and falls back to the newest previously-committed
checkpoint on corruption.
"""

import itertools
import os
import shutil

import numpy as np

import jax
import jax.numpy as jnp

from ..elasticity.config import PeerFailureError, TopologyChangeError
from ..runtime.fp16.loss_scaler import LossScaleState
from ..utils.distributed import BarrierTimeoutError, barrier
from ..utils.logging import log_dist, logger
from . import manifest as mf
from .serialization import (load_obj, save_obj, shard_slice,
                            state_dict_to_tree, tree_to_state_dict,
                            unshard_concat)

LATEST_FILE = mf.LATEST_FILE


class CheckpointTagMismatchError(RuntimeError):
    """Hosts tried to commit a checkpoint under different tags
    (`checkpoint.tag_validation = "FAIL"`): the directory layout keys
    every per-rank file by tag, so divergent tags shear one checkpoint
    into several partial ones."""


_tag_validation_serials = itertools.count()


def check_checkpoint_tag_consistency(tag, fail=False, client=None,
                                     process_index=None, process_count=None,
                                     timeout_s=None, serial=None):
    """Verify every host is saving under the same tag before anything
    is written (reference `engine.py` `_checkpoint_tag_validation`):
    rank 0 publishes its tag on the coordination-service KV store, every
    other rank compares. Returns True when consistent (or unverifiable:
    single process, or no coordination client to compare through —
    logged once at debug, never a failure). On mismatch: warns
    (`tag_validation = "WARN"`) or raises `CheckpointTagMismatchError`
    (`"FAIL"`). The keyword seams (client/process_index/process_count)
    exist so the logic is drivable single-host in tests."""
    tag = str(tag)
    if process_count is None:
        process_count = jax.process_count()
    if process_count <= 1:
        return True
    if client is None:
        from ..utils.distributed import _distributed_client
        client = _distributed_client()
    if client is None:
        logger.debug("checkpoint tag validation skipped: no coordination "
                     "client to compare tags through")
        return True
    if process_index is None:
        process_index = jax.process_index()
    if timeout_s is None:
        from ..utils.distributed import DEFAULT_BARRIER_TIMEOUT_S
        timeout_s = DEFAULT_BARRIER_TIMEOUT_S
    # serial-suffixed keys: every host derives the same serial for the
    # same save-call order (one call per process per save), so repeated
    # saves never read a stale tag. `serial` is an injection seam for
    # single-host tests that simulate several ranks through one counter.
    if serial is None:
        serial = next(_tag_validation_serials)
    key = f"deeperspeed_ckpt_tag/{serial}"
    if process_index == 0:
        client.key_value_set(key, tag)
        return True
    try:
        expect = client.blocking_key_value_get(key, int(timeout_s * 1000))
    except Exception as e:  # noqa: BLE001 - raw gRPC DEADLINE/transport
        # Rank 0 never published (dead, or an emergency save fired on
        # this host only): agreement is UNVERIFIABLE, which is not a
        # mismatch — warn and let the save proceed. Peer liveness is the
        # commit barrier's job; it fails with the typed
        # BarrierTimeoutError -> PeerFailureError discipline, never this
        # advisory check (even in FAIL mode, which gates on a *observed*
        # disagreement, not on a missing peer).
        logger.warning(f"checkpoint tag validation could not compare "
                       f"against rank 0 ({e}); proceeding unverified — "
                       f"the commit barrier still enforces liveness")
        return True
    if isinstance(expect, bytes):
        expect = expect.decode("utf-8")
    if expect == tag:
        return True
    msg = (f"checkpoint tag mismatch across hosts: rank 0 is saving "
           f"{expect!r} but process {process_index} is saving {tag!r} — "
           f"the per-rank files would land in different checkpoint "
           f"directories")
    if fail:
        raise CheckpointTagMismatchError(msg)
    logger.warning(msg)
    return False


def _validate_checkpoint_tag(engine, tag):
    """The `checkpoint.tag_validation` knob's consumer: gate the
    cross-host tag comparison on the parsed mode."""
    cfg = getattr(engine, "_config", None)
    if not getattr(cfg, "checkpoint_tag_validation_enabled", False):
        return
    check_checkpoint_tag_consistency(
        tag, fail=getattr(cfg, "checkpoint_tag_validation_fail", False))


def _commit_barrier(tag):
    """Checkpoint-commit barrier, converted from "hang until deadline"
    into "fail fast and hand off": a `BarrierTimeoutError` (typed, from
    `utils.distributed.barrier`) is re-raised as a `PeerFailureError`
    annotated with the peers the heartbeat monitor considers stale — the
    supervisor then treats the exit as restartable peer loss, and the
    log names the absent host instead of a bare DEADLINE_EXCEEDED."""
    try:
        barrier(tag)
    except BarrierTimeoutError as e:
        from ..elasticity.heartbeat import suspect_peers
        suspects = suspect_peers()
        who = (f"stale-heartbeat peer(s): {suspects}" if suspects else
               "absent peer unknown (no heartbeat monitor is running)")
        logger.error(f"checkpoint commit barrier '{tag}' timed out "
                     f"after {e.elapsed_s:.1f}s — {who}")
        raise PeerFailureError(
            f"checkpoint commit barrier '{tag}' timed out after "
            f"{e.elapsed_s:.1f}s; {who}",
            peers=suspects, staleness_s=e.elapsed_s, cause=e) from e


def _model_states_name(mp_rank):
    return f"mp_rank_{mp_rank:02d}_model_states.pt"


def _zero_ckpt_name(dp_rank, mp_rank):
    return f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states.pt"


def _sharded_dim(spec):
    for i, axis in enumerate(spec):
        if axis is not None:
            return i
    return None


# ---------------------------------------------------------------------------
# phase 1: snapshot (device → host; the only training stall)
# ---------------------------------------------------------------------------

def _pipeline_manifest_info(engine):
    """Stage-partition record for pipelined engines (None otherwise):
    stages, per-stage layer ownership, and the wire schedule — enough
    for tooling to map the pipe-sharded optimizer state back to layers
    without the engine."""
    ps = getattr(engine, "pipeline_schedule", None)
    if not ps:
        return None
    info = {"stages": int(ps["stages"]),
            "n_micro": int(ps["n_micro"]),
            "wire_latency": int(ps["wire_latency"]),
            # "rows" (PipelineModule packed rows — natural tree on disk,
            # restores across any stage count) vs "stacked" (config-
            # driven GPTNeoX — the stacked tree IS the disk layout)
            "layout": ps.get("layout", "rows")}
    if ps.get("layers_per_stage"):
        info["layers_per_stage"] = int(ps["layers_per_stage"])
    if ps.get("parts"):
        # heterogeneous PipelineModule: stage s owns layers
        # [parts[s], parts[s+1]) of the LayerSpec list
        info["parts"] = [int(p) for p in ps["parts"]]
    return info


def snapshot_checkpoint(engine, client_state=None):
    """Build the full ``{relative_path: payload}`` dict for a checkpoint
    of the engine's CURRENT state, with every array materialized on the
    host. After this returns, the payloads are immutable host data —
    training may continue (and mutate ``engine.state``) while a writer
    commits them to disk. Payloads are either picklable objects (written
    via `save_obj`) or raw ``bytes``."""
    if getattr(engine, "_grad_spill", None) is not None:
        raise RuntimeError(
            "snapshot-then-commit saves are not supported on the "
            "streamed-NVMe store-of-record tier: its checkpoint IS the "
            "live segment files (O(1) memory file copies); use the "
            "synchronous save_checkpoint")
    client_state = client_state or {}
    state = engine.state
    dataloader = getattr(engine, "training_dataloader", None)
    gns = getattr(engine, "gradient_noise_scale", None)
    model_state = {
        # natural layout on disk: storage layouts (ZeRO flat-pad, packed
        # pipeline rows) depend on the mesh and must not leak into files
        "module": tree_to_state_dict(engine.params_to_natural(
            state.params)),
        "optimizer": None,
        "lr_scheduler": (engine.lr_scheduler.state_dict()
                         if engine.lr_scheduler is not None else None),
        "batch_size_scheduler": (engine.batch_size_scheduler.state_dict()
                                 if engine.batch_size_scheduler is not None
                                 else None),
        # full-state resume: dataloader position (epoch/offset + sampler
        # seed) and the gradient-noise-scale accumulators ride along so a
        # preempted job restarts on the exact sample stream
        "dataloader": (dataloader.state_dict()
                       if dataloader is not None
                       and hasattr(dataloader, "state_dict") else None),
        "gradient_noise_scale": (gns.state_dict()
                                 if gns is not None else None),
        "csr_tensor_module_names": [],
        # quantization state (EngineState.quant): delayed-scaling amax
        # history + compressed-gradient error feedback — bit-exact
        # resume needs both (docs/quantization.md)
        "quantization_state": (engine._quant_state_dict()
                               if hasattr(engine, "_quant_state_dict")
                               else None),
        "skipped_steps": engine.skipped_steps,
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "dp_world_size": engine.dp_world_size,
        "mp_world_size": engine.mp_world_size,
        # stage-local optimizer state: when a pipeline schedule is
        # active the fp32 masters/moments are sharded over the `pipe`
        # axis, so the manifest records which layer span each stage
        # owns (and loads validate the stage count explicitly)
        "pipeline": _pipeline_manifest_info(engine),
        "loss_scale_state": {
            "cur_scale": float(state.scale.cur_scale),
            "cur_iter": int(state.scale.cur_iter),
            "last_overflow_iter": int(state.scale.last_overflow_iter),
            "cur_hysteresis": int(state.scale.cur_hysteresis),
        },
        "ds_config": engine._config.param_dict,
        "ds_version": "0.3.15+tpu",
    }
    model_state.update(client_state)
    if not engine.zero_optimization():
        model_state["optimizer"] = {
            "state": tree_to_state_dict(
                engine.opt_layout_to_natural(state.opt_state)),
            "param_groups": [dict(g) for g in
                             engine.optimizer.param_groups],
        }
    payloads = {_model_states_name(0): model_state}

    if engine.zero_optimization() or engine.keep_master or \
            getattr(engine, "host_offload", False):
        payloads.update(_zero_payloads(engine))

    # Ship the recovery script with the checkpoint so fp32 weights can be
    # reconstructed later without the framework (reference
    # `engine.py:1800-1808` does the same with its zero_to_fp32.py).
    try:
        from ..utils import zero_to_fp32 as _z2f
        with open(_z2f.__file__, "rb") as f:
            payloads["zero_to_fp32.py"] = f.read()
    except Exception:  # pragma: no cover
        pass
    return payloads


# ---------------------------------------------------------------------------
# phase 2: commit (pure file I/O — shared by the sync path and the
# async writer thread; see manifest.py for the protocol)
# ---------------------------------------------------------------------------

def write_and_commit(payloads, save_dir, tag, step, save_latest=True):
    """Write `payloads` into a staging dir, checksum-manifest + fsync +
    atomically rename it to ``{save_dir}/{tag}``, barrier all hosts, then
    flip ``latest``. Crash at any point leaves either the previous
    committed state or the new one — never a torn pointer. Returns the
    bytes written (0 on non-writer processes)."""
    tag = str(tag)
    nbytes = 0
    if jax.process_index() == 0:
        os.makedirs(save_dir, exist_ok=True)
        staging = os.path.join(save_dir, mf.STAGING_PREFIX + tag)
        if os.path.isdir(staging):  # leftover of a crashed earlier save
            shutil.rmtree(staging)
        os.makedirs(staging)
        entries = {}
        for rel, payload in payloads.items():
            path = os.path.join(staging, rel)
            parent = os.path.dirname(path)
            if parent != staging:
                os.makedirs(parent, exist_ok=True)
            if isinstance(payload, (bytes, bytearray)):
                with open(path, "wb") as f:
                    f.write(payload)
            else:
                save_obj(payload, path)
            mf._fsync_file(path)
            # checksum NOW, while the bytes are still in the page cache —
            # write_manifest would otherwise re-read the whole checkpoint
            entries[rel] = mf.file_entry(path)
            nbytes += entries[rel]["bytes"]
        mf.commit_staged(save_dir, staging, tag, step, files=entries)
    # every host's files are durable before anyone flips/reads latest;
    # the commit barrier fails fast (typed, absent peer recorded) so a
    # host dying mid-save costs seconds, not a hang until the scheduler
    # reaps the job (no-op single-process)
    _commit_barrier("deeperspeed_ckpt_commit")
    if save_latest and jax.process_index() == 0:
        mf.write_latest(save_dir, tag)
    _commit_barrier("deeperspeed_ckpt_latest")
    return nbytes


def save_checkpoint(engine, save_dir, tag=None, client_state=None,
                    save_latest=True):
    client_state = client_state or {}
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    _validate_checkpoint_tag(engine, tag)

    if getattr(engine, "_grad_spill", None) is not None:
        # NVMe store-of-record tier: the segment + optimizer-group files
        # ARE the model state — checkpoint by streaming file copies
        # (O(1) memory), never assembling the tree in DRAM. Beyond-DRAM
        # models can therefore persist/restore; the standard
        # natural-layout format remains for models that fit.
        ckpt_dir = os.path.join(save_dir, str(tag))
        os.makedirs(ckpt_dir, exist_ok=True)
        return _save_streamed_nvme_checkpoint(engine, save_dir, ckpt_dir,
                                              tag, client_state,
                                              save_latest)

    payloads = snapshot_checkpoint(engine, client_state)
    write_and_commit(payloads, save_dir, tag, step=engine.global_steps,
                     save_latest=save_latest)
    log_dist(f"Saved checkpoint {tag} to "
             f"{os.path.join(save_dir, str(tag))}", ranks=[0])
    return True


def _streamed_process_payload(engine, dst_dir):
    """Copy THIS process's NVMe store of record (param segment files +
    optimizer group files) into `dst_dir` and return the per-process
    meta (segments, manifest, optimizer) describing them."""
    state = engine.state
    seg_names = [n for n, _ in engine._stream_plan.segments]
    engine._coord.synchronize_writes()
    for name in seg_names:
        shutil.copyfile(engine._coord.swapper._path(name),
                        os.path.join(dst_dir, f"param_seg_{name}.swp"))
    opt_meta = {"step": engine._host_opt.step_count,
                "param_groups": [dict(g) for g in
                                 engine.optimizer.param_groups]}
    if engine._host_swapper is not None:
        for gid, info in engine._host_swapper.group_info.items():
            for key in info:
                shutil.copyfile(
                    engine._host_swapper._path(gid, key),
                    os.path.join(dst_dir, f"opt_{gid}_{key}.swp"))
        opt_meta["group_info"] = dict(engine._host_swapper.group_info)
    else:
        # DRAM master tier (fits by definition): keep it in the shard
        opt_meta["host_state"] = engine._host_state
    # Param manifest: gid-ordered full-tree paths/shapes/dtypes plus the
    # per-segment byte layout — everything an OFFLINE consumer
    # (utils/zero_to_fp32.py) needs to map the raw .swp files back to
    # named parameters (reference ships zero_to_fp32 inside every
    # checkpoint for the same any-checkpoint-is-recoverable guarantee,
    # `engine.py:1800-1808`).
    from .serialization import _path_key
    flat, _ = jax.tree_util.tree_flatten_with_path(state.params)
    leaf_paths = [_path_key(p) for p, _ in flat]
    leaf_shapes = [tuple(l.shape) for _, l in flat]
    leaf_dtypes = [str(np.dtype(l.dtype)) for _, l in flat]
    segment_layout = {}
    for name in seg_names:
        _, specs = engine._coord._templates[name]
        segment_layout[name] = [
            [int(gid), [int(x) for x in shape], str(np.dtype(dt))]
            for gid, (shape, dt) in zip(engine._seg_idx[name], specs)]
    return {
        "segments": seg_names,
        "param_manifest": {
            "leaf_paths": leaf_paths,
            "leaf_shapes": [list(s) for s in leaf_shapes],
            "leaf_dtypes": leaf_dtypes,
            "segment_layout": segment_layout,
        },
        "optimizer": opt_meta,
    }


def _save_streamed_nvme_checkpoint(engine, save_dir, ckpt_dir, tag,
                                   client_state, save_latest):
    state = engine.state
    n_proc = jax.process_count()
    if n_proc > 1:
        # Every process owns a local NVMe store of record — each writes
        # its own shard directory (the reference's every-rank
        # zero-checkpoint write, `engine.py:1810-1818`, with
        # zero_pp_rank_* naming); process 0 writes the union manifest
        # and `latest` after the barrier.
        pidx = jax.process_index()
        shard_dir = os.path.join(ckpt_dir,
                                 f"zero_pp_rank_{pidx}_mp_rank_00")
        os.makedirs(shard_dir, exist_ok=True)
        payload = _streamed_process_payload(engine, shard_dir)
        save_obj(payload, os.path.join(shard_dir, "streamed_states.pt"),
                 all_ranks=True)
        _commit_barrier("deeperspeed_streamed_save")
        if pidx == 0:
            meta = {
                "streamed_nvme": True,
                "process_count": n_proc,
                "global_steps": engine.global_steps,
                "global_samples": engine.global_samples,
                "skipped_steps": engine.skipped_steps,
                "micro_steps": engine.micro_steps,
                "loss_scale_state": {
                    "cur_scale": float(state.scale.cur_scale),
                    "cur_iter": int(state.scale.cur_iter),
                    "last_overflow_iter": int(
                        state.scale.last_overflow_iter),
                    "cur_hysteresis": int(state.scale.cur_hysteresis),
                },
                "lr_scheduler": (engine.lr_scheduler.state_dict()
                                 if engine.lr_scheduler is not None
                                 else None),
                "ds_version": "0.3.15+tpu",
            }
            meta.update(client_state)
            save_obj(meta, os.path.join(ckpt_dir, _model_states_name(0)))
        # all shard writers (and the meta write) are durable before the
        # pointer flips — `latest` can never name a checkpoint some host
        # never finished
        _commit_barrier("deeperspeed_streamed_save2")
        if save_latest and pidx == 0:
            mf.write_latest(save_dir, tag)
        _commit_barrier("deeperspeed_streamed_latest")
        log_dist(f"Saved streamed-NVMe checkpoint {tag} to {ckpt_dir} "
                 f"({n_proc} process shards)", ranks=[0])
        return True

    payload = _streamed_process_payload(engine, ckpt_dir)
    meta = {
        "streamed_nvme": True,
        "segments": payload["segments"],
        "param_manifest": payload["param_manifest"],
        "optimizer": payload["optimizer"],
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "skipped_steps": engine.skipped_steps,
        "micro_steps": engine.micro_steps,
        "loss_scale_state": {
            "cur_scale": float(state.scale.cur_scale),
            "cur_iter": int(state.scale.cur_iter),
            "last_overflow_iter": int(state.scale.last_overflow_iter),
            "cur_hysteresis": int(state.scale.cur_hysteresis),
        },
        "lr_scheduler": (engine.lr_scheduler.state_dict()
                         if engine.lr_scheduler is not None else None),
        "ds_version": "0.3.15+tpu",
    }
    meta.update(client_state)
    save_obj(meta, os.path.join(ckpt_dir, _model_states_name(0)))
    if save_latest:
        mf.write_latest(save_dir, tag)
    log_dist(f"Saved streamed-NVMe checkpoint {tag} to {ckpt_dir}",
             ranks=[0])
    return True


def _load_streamed_nvme_checkpoint(engine, ckpt_dir, meta):
    """Restore by streaming files back into the engine's NVMe store.

    Multi-process checkpoints (per-process `zero_pp_rank_*` shard dirs)
    restore process-locally: each process reads back exactly the store
    it wrote. Elastic re-slicing is not supported on this tier — the
    NVMe store of record is process-local by construction."""
    saved_procs = int(meta.get("process_count", 1))
    if saved_procs > 1:
        if saved_procs != jax.process_count():
            raise RuntimeError(
                f"streamed-NVMe checkpoint was saved by {saved_procs} "
                f"processes but {jax.process_count()} are running; "
                "elastic resume is not supported on this tier (restore "
                "with the saving process count, then re-save)")
        shard_dir = os.path.join(
            ckpt_dir, f"zero_pp_rank_{jax.process_index()}_mp_rank_00")
        payload = load_obj(os.path.join(shard_dir, "streamed_states.pt"))
        counters = dict(meta)
        counters.pop("process_count")   # shard payload is single-process
        counters.update(payload)        # segments/manifest/optimizer
        # return the rank-independent ckpt_dir (every other load path
        # does), not the per-rank shard dir the recursion restored from
        _, client_state = _load_streamed_nvme_checkpoint(
            engine, shard_dir, counters)
        return ckpt_dir, client_state
    for name in meta["segments"]:
        shutil.copyfile(os.path.join(ckpt_dir, f"param_seg_{name}.swp"),
                        engine._coord.swapper._path(name))
    opt = meta["optimizer"]
    engine._host_opt.step_count = opt.get("step", 0)
    engine.optimizer.param_groups = [dict(g) for g in opt["param_groups"]]
    if engine._host_swapper is not None:
        engine._host_swapper.group_info = {
            int(k): v for k, v in opt["group_info"].items()}
        for gid, info in engine._host_swapper.group_info.items():
            for key in info:
                shutil.copyfile(
                    os.path.join(ckpt_dir, f"opt_{gid}_{key}.swp"),
                    engine._host_swapper._path(gid, key))
    else:
        engine._host_state = opt["host_state"]

    engine.global_steps = meta.get("global_steps", 0)
    engine.global_samples = meta.get("global_samples", 0)
    engine.skipped_steps = meta.get("skipped_steps", 0)
    engine.micro_steps = meta.get("micro_steps", 0)
    if meta.get("lr_scheduler") is not None and \
            engine.lr_scheduler is not None:
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    ls = meta.get("loss_scale_state", {})
    engine.state = engine.state._replace(
        scale=LossScaleState(
            cur_scale=jnp.asarray(ls.get("cur_scale", 1.0), jnp.float32),
            cur_iter=jnp.asarray(ls.get("cur_iter", 0), jnp.int32),
            last_overflow_iter=jnp.asarray(
                ls.get("last_overflow_iter", -1), jnp.int32),
            cur_hysteresis=jnp.asarray(ls.get("cur_hysteresis", 1),
                                       jnp.int32)),
        global_steps=jnp.asarray(engine.global_steps, jnp.int32),
        skipped_steps=jnp.asarray(engine.skipped_steps, jnp.int32))
    client_state = {k: v for k, v in meta.items()
                    if k not in ("streamed_nvme", "segments", "optimizer",
                                 "loss_scale_state", "lr_scheduler",
                                 "param_manifest", "process_count")}
    log_dist(f"Loaded streamed-NVMe checkpoint from {ckpt_dir}", ranks=[0])
    return ckpt_dir, client_state


def _flat_arrays(tree):
    """{path: numpy array} view of a pytree (device_get applied)."""
    sd = tree_to_state_dict(tree)
    return sd["arrays"]


def _zero_payloads(engine):
    if getattr(engine, "host_offload", False):
        return {_zero_ckpt_name(0, 0): _host_offload_payload(engine)}
    state = engine.state
    rules = engine.zero_rules
    dp = engine.dp_world_size if rules.stage >= 1 else 1

    # Flat-padded (ragged) leaves are saved in natural shape so files are
    # world-size independent (padding depends on dp world); they re-pad on
    # load. Replication of these slices per rank mirrors the reference's
    # handling of unpartitioned state.
    master_flat = (_flat_arrays(engine.layout_to_natural(state.master))
                   if state.master is not None else None)
    opt_flat = _flat_arrays(engine.opt_layout_to_natural(state.opt_state))

    def dims_of(flat):
        """Per-key slicing rule: an int dim (evenly-sharded leaves), the
        string "flat" (ragged leaves — saved as rank slices of the
        raveled natural array so the biggest fp32 state is never
        duplicated dp times on disk), or None (replicate)."""
        out = {}
        for k, v in flat.items():
            if rules.master_pad_info(v.shape) is not None:
                out[k] = "flat"
            else:
                out[k] = _sharded_dim(rules.master_spec(v.shape))
        return out

    master_dims = dims_of(master_flat) if master_flat is not None else None
    opt_dims = dims_of(opt_flat)

    def shapes_of(flat, dims):
        return {k: tuple(v.shape) for k, v in flat.items()
                if dims[k] == "flat"}

    payloads = {}
    for dp_rank in range(dp):
        def slice_flat(flat, dims):
            out = {}
            for key, arr in flat.items():
                dim = dims[key]
                if dim is None or dp == 1:
                    out[key] = arr  # replicated leaf: duplicated per rank
                elif dim == "flat":
                    out[key] = shard_slice(np.ravel(arr), dp, dp_rank, 0)
                else:
                    out[key] = shard_slice(arr, dp, dp_rank, dim)
            return out

        payloads[_zero_ckpt_name(dp_rank, 0)] = {
            "optimizer_state_dict": {
                "state": slice_flat(opt_flat, opt_dims),
                "shard_dims": opt_dims,
                "flat_shapes": shapes_of(opt_flat, opt_dims),
                "param_groups": [dict(g) for g in
                                 engine.optimizer.param_groups],
            },
            "fp32_master": (slice_flat(master_flat, master_dims)
                            if master_flat is not None else None),
            "fp32_master_dims": master_dims,
            "fp32_master_flat_shapes": (
                shapes_of(master_flat, master_dims)
                if master_flat is not None else None),
            "zero_stage": rules.stage,
            "partition_count": dp,
            "dp_rank": dp_rank,
        }
    return payloads


def _host_offload_payload(engine):
    """ZeRO-Offload: host-resident (or NVMe) masters/moments, one file."""
    if engine._host_swapper is not None:
        groups = {i: engine._host_swapper.load_group(i)
                  for i in range(len(engine._host_shapes))}
        masters = [groups[i]["master"] for i in range(len(groups))]
        ms = [groups[i]["exp_avg"] for i in range(len(groups))]
        vs = [groups[i]["exp_avg_sq"] for i in range(len(groups))]
    else:
        hs = engine._host_state
        masters, ms, vs = hs["master"], hs["m"], hs["v"]
    # Path keys + shapes let the offline zero_to_fp32 script map the flat
    # host masters back to named parameters without the engine.
    from .serialization import _path_key
    flat, _ = jax.tree_util.tree_flatten_with_path(engine.state.params)
    param_paths = [_path_key(path) for path, _ in flat]
    return {
        "optimizer_state_dict": {
            "host_offload": True,
            "master": masters,
            "exp_avg": ms,
            "exp_avg_sq": vs,
            "step": engine._host_opt.step_count,
            "param_groups": [dict(g) for g in
                             engine.optimizer.param_groups],
            "param_paths": param_paths,
            "param_shapes": [tuple(s) for s in engine._host_shapes],
        },
        "fp32_master": None,
        "zero_stage": engine.zero_rules.stage,
        "partition_count": 1,
        "dp_rank": 0,
    }


def _load_host_offload_checkpoint(engine, shard):
    sd = shard["optimizer_state_dict"]
    masters = [np.ascontiguousarray(m, np.float32) for m in sd["master"]]
    ms = [np.ascontiguousarray(m, np.float32) for m in sd["exp_avg"]]
    vs = [np.ascontiguousarray(m, np.float32) for m in sd["exp_avg_sq"]]
    engine._host_opt.step_count = sd.get("step", 0)
    engine.optimizer.param_groups = [dict(g) for g in sd["param_groups"]]
    if engine._host_swapper is not None:
        for i, (mast, m, v) in enumerate(zip(masters, ms, vs)):
            engine._host_swapper.initialize_group(
                i, {"master": mast, "exp_avg": m, "exp_avg_sq": v})
    else:
        engine._host_state = {"master": masters, "m": ms, "v": vs}
    # Rebuild compute params from the restored masters: into the host/
    # NVMe store under param offload, onto the device otherwise.
    import jax.numpy as jnp
    if getattr(engine, "param_offload", False):
        natural = jax.tree_util.tree_unflatten(
            engine._host_treedef,
            [m.reshape(s) for m, s in zip(masters, engine._host_shapes)])
        # cpu tier: in-place host-store write; nvme tier: segment
        # swap-outs through the coordinator (no DRAM mirror exists)
        return engine.params_from_natural(natural)
    leaves = [jnp.asarray(m.reshape(s), engine.compute_dtype)
              for m, s in zip(masters, engine._host_shapes)]
    params = jax.tree_util.tree_unflatten(engine._host_treedef, leaves)
    params = jax.tree_util.tree_map(
        lambda p, sh: jax.device_put(p, sh), params, engine._param_sh)
    return params


def _resolve_committed_state(load_dir, tag):
    """Shared candidate walk of the full-state and params-only loaders:
    verify the requested tag's manifest and deserialize its model
    states; when resuming from `latest` (tag=None), fall back to the
    newest other COMMITTED checkpoint on corruption — a torn write of
    the newest save costs at most one checkpoint interval, never the
    job. Returns (tag, ckpt_dir, model_state) or (None, None, None)."""
    explicit_tag = tag is not None
    if tag is None:
        tag = mf.read_latest(load_dir)
        if tag is None:
            logger.warning(f"No '{LATEST_FILE}' file at "
                           f"{os.path.join(load_dir, LATEST_FILE)}; "
                           "cannot resume")
            return None, None, None

    candidates = [str(tag)]
    if not explicit_tag:
        candidates += [t for _, t in reversed(mf.committed_tags(load_dir))
                       if t != str(tag)]

    for cand in candidates:
        ckpt_dir = os.path.join(load_dir, cand)
        ok, problems = mf.verify_manifest(ckpt_dir)
        if not ok:
            if explicit_tag:
                # the user named THIS checkpoint: corruption must be
                # loud, not a silent (None, {}) that reads as "start
                # fresh" to resume scripts
                raise RuntimeError(
                    f"checkpoint {cand} failed manifest verification: "
                    f"{'; '.join(problems[:3])}")
            logger.warning(
                f"Checkpoint {cand} failed manifest verification "
                f"({'; '.join(problems[:3])}); falling back to the "
                "previous committed checkpoint")
            continue
        model_path = os.path.join(ckpt_dir, _model_states_name(0))
        if not os.path.isfile(model_path):
            logger.warning(f"Checkpoint file {model_path} not found")
            continue
        try:
            model_state = load_obj(model_path)
        except Exception as e:  # torn legacy write (no manifest to catch)
            if explicit_tag:
                raise RuntimeError(
                    f"checkpoint {cand} is corrupt: failed to "
                    f"deserialize {model_path}") from e
            logger.warning(f"Failed to deserialize {model_path} "
                           f"({type(e).__name__}: {e})")
            continue
        if cand != str(tag):
            logger.warning(f"Resuming from fallback checkpoint {cand} "
                           f"instead of corrupt {tag}")
        return cand, ckpt_dir, model_state

    logger.warning(f"No loadable checkpoint under {load_dir}")
    return None, None, None


# model-state keys that are training state, not caller payload: both
# full and module-only loads exclude them from the returned client_state
_TRAINING_STATE_KEYS = ("module", "optimizer", "lr_scheduler",
                        "batch_size_scheduler", "dataloader",
                        "gradient_noise_scale", "quantization_state")


def _client_state(model_state):
    return {k: v for k, v in model_state.items()
            if k not in _TRAINING_STATE_KEYS}


def _module_state_view(model_state, load_dir, tag, like):
    """Shared body of the params-only loaders: reject streamed-NVMe
    saves (their params ARE the segment store — use a full load on an
    offload_param engine) and return (natural_params, client_state)."""
    if model_state.get("streamed_nvme"):
        raise RuntimeError(
            "module-only load is unsupported for streamed-NVMe "
            "checkpoints: their params ARE the segment store (use a "
            "full load on an offload_param engine)")
    params = state_dict_to_tree(model_state["module"], like=like)
    log_dist(f"Loaded module-only checkpoint {tag} from {load_dir}",
             ranks=[0])
    return params, _client_state(model_state)


def load_checkpoint(engine, load_dir, tag=None, load_optimizer_states=True,
                    load_lr_scheduler_states=True,
                    load_dataloader_states=True, module_only=False):
    cand, ckpt_dir, model_state = _resolve_committed_state(load_dir, tag)
    if cand is None:
        return None, {}
    if module_only:
        # params-only restore (serving restarts / weight-only warm
        # starts): manifest CRC + fallback ran above exactly as for a
        # full resume, but optimizer moments, schedulers, dataloader
        # position, loss scale and counters are never deserialized or
        # touched — the engine keeps its current training state
        params_np, client_state = _module_state_view(
            model_state, load_dir, cand, engine.params_natural_like())
        params = engine.params_from_natural(params_np)
        engine.state = engine.state._replace(params=params)
        if getattr(engine, "keep_master", False) and \
                engine.state.master is not None:
            # fp32 masters were intentionally left alone: the next
            # optimizer step recomputes params FROM them, discarding
            # these weights — module_only is for eval/serving engines,
            # not for continuing training
            logger.warning(
                "module_only load on an engine with fp32 masters: the "
                "next train step overwrites params from the (stale) "
                "masters — use module_only for evaluation/serving only")
        return os.path.join(load_dir, cand), client_state
    return _apply_checkpoint(engine, load_dir, cand, ckpt_dir,
                             model_state, load_optimizer_states,
                             load_lr_scheduler_states,
                             load_dataloader_states)


def load_module_checkpoint(load_dir, tag=None, like=None):
    """Engine-free params-only load for the serving stack: the same
    manifest verification + committed-tag fallback as `load_checkpoint`,
    returning the NATURAL module pytree (host numpy leaves) without an
    engine to hang state off. `like` supplies the expected tree
    structure (paths are matched, so dtype/layout of the template do
    not matter). Returns (path, params, client_state) or (None, None,
    {})."""
    cand, ckpt_dir, model_state = _resolve_committed_state(load_dir, tag)
    if cand is None:
        return None, None, {}
    params, client_state = _module_state_view(model_state, load_dir, cand,
                                              like)
    return os.path.join(load_dir, cand), params, client_state


def _apply_checkpoint(engine, load_dir, tag, ckpt_dir, model_state,
                      load_optimizer_states, load_lr_scheduler_states,
                      load_dataloader_states=True):
    if model_state.get("streamed_nvme"):
        if getattr(engine, "_grad_spill", None) is None:
            raise RuntimeError(
                "this checkpoint was saved by the NVMe store-of-record "
                "tier (streamed file copies); load it with an "
                "offload_param {device: nvme} engine")
        return _load_streamed_nvme_checkpoint(engine, ckpt_dir,
                                              model_state)

    # --- topology guard (elastic resume rules) ----------------------------
    # dp world changes are ABSORBED: the zero-shard merge below re-slices
    # the saved partitions with the current shardings, and host-side
    # per-replica state reconciles under the new replica count. mp/model-
    # axis changes are REJECTED loudly: model-parallel layouts differ
    # structurally (packed rows, per-shard fusion), and a silent re-place
    # would corrupt the weights.
    # Pipeline-stage topology: checkpoints store the NATURAL layout, so
    # a PIPE-axis change re-partitions cleanly (packed rows repack, the
    # stacked blocks re-place) — it is absorbed like a dp change, not
    # rejected like a model-axis change. Two hard walls remain:
    #   (a) the config-driven GPTNeoX pipeline's "stacked" layout IS the
    #       natural tree on disk ([L, ...] blocks + head), structurally
    #       different from the sequential model's per-layer list — a
    #       cross-layout load would fail deep in tree matching;
    #   (b) the MODEL axis (tensor slicing) still rejects — that factor
    #       is isolated by dividing the saved/current pipe stages out of
    #       mp_world_size (the non-data product).
    saved_pipe = model_state.get("pipeline") or {}
    cur_pipe = _pipeline_manifest_info(engine) or {}
    saved_stages = max(1, int(saved_pipe.get("stages", 1)))
    cur_stages = max(1, int(cur_pipe.get("stages", 1)))
    if (saved_pipe.get("layout") == "stacked") != \
            (cur_pipe.get("layout") == "stacked"):
        side = "saved by" if saved_pipe.get("layout") == "stacked" \
            else "loading into"
        raise TopologyChangeError(
            f"this checkpoint was {side} a config-driven pipeline "
            f"engine whose stacked [L, ...] block layout IS the tree on "
            f"disk: it only restores into an engine running the same "
            f"'pipeline' block (any stage count), not a sequential one "
            f"— add/drop the block to match, or convert offline")

    saved_mp = model_state.get("mp_world_size")
    if saved_mp is not None:
        saved_model_world = max(1, int(saved_mp) // saved_stages)
        cur_model_world = max(1, int(engine.mp_world_size) // cur_stages)
        if saved_model_world != cur_model_world:
            raise TopologyChangeError(
                f"checkpoint was saved at model-axis world "
                f"{saved_model_world} (mp_world_size={saved_mp} / "
                f"{saved_stages} pipeline stage(s)) but this engine "
                f"runs model-axis world {cur_model_world}: model-axis "
                f"topology changes cannot be elastically resumed — "
                f"restore the original mesh, or re-shard the "
                f"checkpoint offline")
    if saved_stages != cur_stages:
        log_dist(
            f"elastic resume: pipeline stages changed {saved_stages} "
            f"-> {cur_stages}; the natural-layout checkpoint "
            f"re-partitions under the current mesh (optimizer state "
            f"re-shards stage-local)", ranks=[0])

    saved_dp = model_state.get("dp_world_size")
    dp_changed = (saved_dp is not None and
                  int(saved_dp) != int(engine.dp_world_size))
    if dp_changed:
        log_dist(
            f"elastic resume: dp world size changed {saved_dp} -> "
            f"{engine.dp_world_size}; zero shards re-slice under the "
            f"current mesh, the dataloader stream re-deals under the "
            f"new replica count (epoch preserved, offset reset), and "
            f"the global batch is now "
            f"{engine.train_batch_size()} samples/step", ranks=[0])

    # --- params -----------------------------------------------------------
    params_np = state_dict_to_tree(model_state["module"],
                                   like=engine.params_natural_like())
    params = engine.params_from_natural(params_np)

    master = engine.state.master
    opt_state = engine.state.opt_state

    # --- optimizer --------------------------------------------------------
    if load_optimizer_states:
        if getattr(engine, "host_offload", False):
            shard_path = os.path.join(ckpt_dir, _zero_ckpt_name(0, 0))
            if os.path.isfile(shard_path):
                params = _load_host_offload_checkpoint(
                    engine, load_obj(shard_path))
        elif engine.zero_optimization() or engine.keep_master:
            master, opt_state = _load_zero_checkpoint(engine, ckpt_dir)
        elif model_state.get("optimizer"):
            opt_natural = engine.opt_layout_to_natural(
                engine.state.opt_state)
            try:
                opt_np = state_dict_to_tree(
                    model_state["optimizer"]["state"], like=opt_natural)
            except (KeyError, ValueError, TypeError) as e:
                if getattr(engine.optimizer, "packed_transport", False):
                    # layout break: packed_transport error-feedback state
                    # changed from per-leaf trees to one flat
                    # [world, wire_pad] buffer pair (round 4); old
                    # checkpoints cannot restore onto the packed wire
                    raise RuntimeError(
                        "optimizer state restore failed and this engine "
                        "runs a 1-bit optimizer with packed_transport: "
                        "checkpoints saved before the packed-wire layout "
                        "(error feedback as one flat [world, wire_pad] "
                        "buffer pair) cannot be restored. Re-save the "
                        "checkpoint with packed_transport disabled, or "
                        "resume without optimizer states "
                        f"(load_optimizer_states=False). Cause: {e}"
                    ) from e
                raise
            opt_state = engine.opt_natural_to_layout(
                opt_np, engine.state.opt_state)
            engine.optimizer.param_groups = [
                dict(g) for g in model_state["optimizer"]["param_groups"]]

    # --- schedulers / counters / host-side training state ----------------
    if load_lr_scheduler_states and engine.lr_scheduler is not None and \
            model_state.get("lr_scheduler") is not None:
        engine.lr_scheduler.load_state_dict(model_state["lr_scheduler"])
    if engine.batch_size_scheduler is not None and \
            model_state.get("batch_size_scheduler") is not None:
        engine.batch_size_scheduler.load_state_dict(
            model_state["batch_size_scheduler"])
    # load_dataloader_states=False: sentinel rollback keeps the loader at
    # its CURRENT position (already past the quarantined window) instead
    # of rewinding it to the checkpoint's offset
    dataloader = getattr(engine, "training_dataloader", None)
    if load_dataloader_states and dataloader is not None and \
            hasattr(dataloader, "load_state_dict") and \
            model_state.get("dataloader") is not None:
        try:
            dataloader.load_state_dict(model_state["dataloader"])
        except ValueError as e:
            # elastic restarts legitimately change batch size / replica
            # count: an exact position restore is then impossible — the
            # downgrade-to-warn path RECONCILES instead of aborting a
            # half-applied load: epoch + seed (order-independent across
            # topologies) are kept, the batch offset resets, and the
            # stream re-deals under the current replica count
            if hasattr(dataloader, "reconcile_state_dict"):
                kept = dataloader.reconcile_state_dict(
                    model_state["dataloader"])
                logger.warning(
                    f"dataloader position not restored exactly ({e}); "
                    f"reconciled under the current topology instead: "
                    f"{kept}")
            else:
                logger.warning(f"dataloader position not restored ({e});"
                               " resuming from the start of the epoch")
    gns = getattr(engine, "gradient_noise_scale", None)
    if gns is not None and \
            model_state.get("gradient_noise_scale") is not None:
        gns.load_state_dict(model_state["gradient_noise_scale"])
        if dp_changed:
            # the mid-window buffer accumulates micro-grads from the OLD
            # sample stream; under a re-dealt stream those partial sums
            # would pair batches that never co-occurred — drop the
            # window, keep the (topology-independent) EMA estimates
            gns.reconcile_topology()

    engine.global_steps = model_state.get("global_steps", 0)
    engine.global_samples = model_state.get("global_samples", 0)
    engine.skipped_steps = model_state.get("skipped_steps", 0)
    engine.micro_steps = model_state.get("micro_steps", 0)

    ls = model_state.get("loss_scale_state", {})
    scale_state = LossScaleState(
        cur_scale=jnp.asarray(ls.get("cur_scale", 1.0), jnp.float32),
        cur_iter=jnp.asarray(ls.get("cur_iter", 0), jnp.int32),
        last_overflow_iter=jnp.asarray(ls.get("last_overflow_iter", -1),
                                       jnp.int32),
        cur_hysteresis=jnp.asarray(ls.get("cur_hysteresis", 1), jnp.int32))

    engine.state = engine.state._replace(
        params=params, master=master, opt_state=opt_state,
        scale=scale_state,
        global_steps=jnp.asarray(engine.global_steps, jnp.int32),
        skipped_steps=jnp.asarray(engine.skipped_steps, jnp.int32))

    # quantization state (amax history / compressed-grad error feedback):
    # restored AFTER the state replace so the engine's reconciliation
    # (dp-change EF reshape rules) sees the final topology
    if hasattr(engine, "_restore_quant_state"):
        engine._restore_quant_state(model_state.get("quantization_state"))

    client_state = _client_state(model_state)
    log_dist(f"Loaded checkpoint {tag} from {load_dir}", ranks=[0])
    return os.path.join(load_dir, str(tag)), client_state


def _load_zero_checkpoint(engine, ckpt_dir):
    """Merge per-dp-rank zero shards (possibly from a different world size)
    and re-place with current shardings — elastic resume."""
    rules = engine.zero_rules
    shards = []
    dp_rank = 0
    while True:
        path = os.path.join(ckpt_dir, _zero_ckpt_name(dp_rank, 0))
        if not os.path.isfile(path):
            break
        shards.append(load_obj(path))
        dp_rank += 1
    if not shards:
        logger.warning(f"No zero checkpoint files in {ckpt_dir}")
        return engine.state.master, engine.state.opt_state

    saved_dp = shards[0]["partition_count"]

    def merge_flat(flats, dims, flat_shapes=None):
        """Merge per-rank {path: slice} dicts back to full natural-shaped
        arrays. "flat"-sliced (ragged) leaves concat their raveled rank
        slices and reshape to the recorded natural shape."""
        out = {}
        for key in flats[0]:
            dim = dims.get(key) if dims else None
            if dim is None or saved_dp == 1:
                out[key] = flats[0][key]
            elif dim == "flat":
                merged = unshard_concat([f[key] for f in flats], 0)
                out[key] = merged.reshape((flat_shapes or {})[key])
            else:
                out[key] = unshard_concat([f[key] for f in flats], dim)
        return out

    opt_flats = [s["optimizer_state_dict"]["state"] for s in shards]
    opt_dims = shards[0]["optimizer_state_dict"].get("shard_dims", {})
    opt_full = merge_flat(
        opt_flats, opt_dims,
        shards[0]["optimizer_state_dict"].get("flat_shapes"))

    master_full = None
    if shards[0].get("fp32_master") is not None:
        master_flats = [s["fp32_master"] for s in shards]
        master_full = merge_flat(master_flats,
                                 shards[0].get("fp32_master_dims", {}),
                                 shards[0].get("fp32_master_flat_shapes"))

    master = engine.state.master
    if master is not None and master_full is not None:
        # like= must carry the NATURAL tree structure: saved keys are
        # natural-layout paths (packed-rows engines store per-layer keys)
        master_np = state_dict_to_tree(
            {"arrays": master_full},
            like=engine.layout_to_natural(engine.state.master))
        master = engine.natural_to_layout(master_np, engine.state.master)
    opt_state = engine.state.opt_state
    if opt_full:
        opt_np = state_dict_to_tree(
            {"arrays": opt_full},
            like=engine.opt_layout_to_natural(engine.state.opt_state))
        opt_state = engine.opt_natural_to_layout(opt_np,
                                                 engine.state.opt_state)
        engine.optimizer.param_groups = [
            dict(g) for g in shards[0]["optimizer_state_dict"]
            ["param_groups"]]
    return master, opt_state
