"""Crash-consistent checkpoint commit protocol: staging dirs, per-file
checksum manifests, atomic `latest` flips, and retention GC.

Snapshot-then-commit write path (shared by the sync save and the
AsyncCheckpointManager's background writer):

1. all files are written into ``{save_dir}/tmp.{tag}`` and fsynced;
2. ``manifest.json`` (per-file byte size + crc32) is written last, also
   via tmp+fsync+rename — a checkpoint directory is *committed* iff it
   holds a parseable manifest;
3. the staging dir is atomically renamed to ``{save_dir}/{tag}`` and the
   parent dir fsynced — a crash at any earlier point leaves only a
   ``tmp.*`` dir that readers ignore;
4. (multihost) ``sync_global_devices`` — every host's files are durable
   before any host advances;
5. ``latest`` flips via tmp+fsync+rename, strictly after the barrier, so
   it can never point at a checkpoint another host has not finished.

Readers (``load_checkpoint``) verify sizes+checksums against the manifest
and fall back to the newest other committed tag on mismatch; retention GC
(`gc_checkpoints`) only ever deletes *committed* checkpoints and never
the one ``latest`` points to.
"""

import json
import os
import shutil
import zlib

LATEST_FILE = "latest"
MANIFEST_FILE = "manifest.json"
MANIFEST_FORMAT = 1
STAGING_PREFIX = "tmp."


class ManifestError(Exception):
    """A manifest file exists but is unreadable/malformed (distinct from a
    legacy checkpoint that never had one)."""


def _fsync_file(path):
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(path):  # directory entries themselves need an fsync for
    try:                # the rename to be durable (POSIX); best-effort on
        fd = os.open(path, os.O_RDONLY)  # platforms without dir fds
    except OSError:  # pragma: no cover
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def file_crc32(path, chunk_bytes=1 << 20):
    """Streaming crc32 of a file (constant memory)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def atomic_write_text(path, text):
    """Write `text` to `path` via tmp+fsync+rename: readers see either the
    old contents or the new, never a torn write."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def file_entry(path):
    """One manifest entry for an on-disk file."""
    return {"bytes": os.path.getsize(path),
            "crc32": f"{file_crc32(path):08x}"}


def write_manifest(ckpt_dir, tag, step, extra=None, files=None):
    """Checksum every file under `ckpt_dir` (recursively — streamed-NVMe
    checkpoints hold per-process shard subdirs) into MANIFEST_FILE. A
    writer that already checksummed while staging passes the entries via
    `files` ({rel: {bytes, crc32}}) and skips the re-read pass."""
    if files is None:
        files = {}
        for root, _, names in os.walk(ckpt_dir):
            for name in names:
                if root == ckpt_dir and name == MANIFEST_FILE:
                    continue
                path = os.path.join(root, name)
                files[os.path.relpath(path, ckpt_dir)] = file_entry(path)
    manifest = {"format": MANIFEST_FORMAT, "tag": str(tag),
                "step": int(step), "files": files}
    if extra:
        manifest.update(extra)
    atomic_write_text(os.path.join(ckpt_dir, MANIFEST_FILE),
                      json.dumps(manifest, indent=1, sort_keys=True))
    return manifest


def load_manifest(ckpt_dir):
    """The parsed manifest, or None when the checkpoint predates the
    commit protocol (legacy, unverifiable). Raises ManifestError when a
    manifest exists but cannot be parsed (torn write => not committed)."""
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            manifest = json.load(f)
        if not isinstance(manifest.get("files"), dict):
            raise ValueError("manifest has no 'files' table")
        return manifest
    except (ValueError, OSError) as e:
        raise ManifestError(f"unreadable manifest at {path}: {e}") from e


def verify_manifest(ckpt_dir):
    """(ok, problems): re-checksum every manifest entry. A legacy dir
    without a manifest verifies vacuously (nothing to check against)."""
    try:
        manifest = load_manifest(ckpt_dir)
    except ManifestError as e:
        return False, [str(e)]
    if manifest is None:
        return True, []
    problems = []
    for rel, info in manifest["files"].items():
        path = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(path):
            problems.append(f"{rel}: missing")
            continue
        size = os.path.getsize(path)
        if size != info["bytes"]:
            problems.append(f"{rel}: {size} bytes, manifest says "
                            f"{info['bytes']}")
        elif f"{file_crc32(path):08x}" != info["crc32"]:
            problems.append(f"{rel}: crc32 mismatch")
    return not problems, problems


def is_committed(ckpt_dir):
    try:
        return load_manifest(ckpt_dir) is not None
    except ManifestError:
        return False


def committed_tags(save_dir):
    """[(step, tag)] of committed checkpoints, sorted oldest → newest."""
    out = []
    try:
        names = os.listdir(save_dir)
    except OSError:
        return out
    for name in names:
        if name.startswith(STAGING_PREFIX):
            continue
        ckpt_dir = os.path.join(save_dir, name)
        if not os.path.isdir(ckpt_dir):
            continue
        try:
            manifest = load_manifest(ckpt_dir)
        except ManifestError:
            continue
        if manifest is None:
            continue
        out.append((int(manifest.get("step", -1)), name))
    out.sort()
    return out


def read_latest(save_dir):
    path = os.path.join(save_dir, LATEST_FILE)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        tag = f.read().strip()
    return tag or None


def write_latest(save_dir, tag):
    atomic_write_text(os.path.join(save_dir, LATEST_FILE), str(tag))


def commit_staged(save_dir, staging_dir, tag, step, extra=None,
                  files=None):
    """Finalize a fully-written staging dir: manifest, fsync, atomic
    rename onto `{save_dir}/{tag}`. Does NOT flip `latest` — that happens
    after the multihost barrier (see module docstring)."""
    final = os.path.join(save_dir, str(tag))
    write_manifest(staging_dir, tag, step, extra=extra, files=files)
    _fsync_dir(staging_dir)
    if os.path.isdir(final):
        # Re-save of an existing tag: move the old commit aside BEFORE
        # the new one lands — deleting it first would open a crash
        # window with neither version on disk, breaking the "old state
        # or new state, never nothing" guarantee. The aside dir keeps
        # its manifest, so if we crash mid-swap it is still a committed
        # checkpoint that fallback loading can find; the happy path
        # removes it right after the swap.
        aside = os.path.join(save_dir, str(tag) + ".replaced")
        if os.path.isdir(aside):
            shutil.rmtree(aside)
        os.rename(final, aside)
        os.replace(staging_dir, final)
        _fsync_dir(save_dir)
        shutil.rmtree(aside, ignore_errors=True)
    else:
        os.replace(staging_dir, final)
        _fsync_dir(save_dir)
    return final


def gc_checkpoints(save_dir, keep_last_n=0, keep_every_n_steps=0,
                   protect=()):
    """Retention policy over *committed* checkpoints only: keep the newest
    `keep_last_n`, plus every tag whose step is a multiple of
    `keep_every_n_steps`, plus whatever `latest` points to and any
    `protect`-ed tags. Uncommitted dirs (no manifest — e.g. a save that
    crashed mid-write, or a foreign dir) are never touched. Returns the
    deleted tags."""
    if not keep_last_n and not keep_every_n_steps:
        return []
    tags = committed_tags(save_dir)
    keep = {str(t) for t in protect}
    latest = read_latest(save_dir)
    if latest is not None:
        keep.add(latest)
    if keep_last_n:
        keep.update(tag for _, tag in tags[-int(keep_last_n):])
    if keep_every_n_steps:
        keep.update(tag for step, tag in tags
                    if step >= 0 and step % int(keep_every_n_steps) == 0)
    deleted = []
    for _, tag in tags:
        if tag in keep:
            continue
        shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
        deleted.append(tag)
    return deleted
