from . import manifest  # noqa: F401
from .async_manager import AsyncCheckpointManager  # noqa: F401
from .checkpointing import (load_checkpoint, save_checkpoint,  # noqa: F401
                            snapshot_checkpoint, write_and_commit)
