"""Fault-tolerant asynchronous checkpointing.

`AsyncCheckpointManager` turns the two-phase save in `checkpointing.py`
into an overlap-with-training pipeline: `save_async` snapshots the train
state to host at a step boundary (the only stall — device→host transfer
plus host-side shard slicing), then serializes and commits in a
background writer thread while training dispatches the next steps.
ZeRO-Infinity's core observation (arxiv 2104.07857) is that persistence
I/O must overlap compute to be free at scale; on preemptible TPU fleets
the same machinery is what keeps goodput high — a SIGTERM from the
scheduler triggers an emergency save at the next step boundary instead
of losing the whole interval since the last checkpoint.

Guarantees:

- at most ONE save is in flight; a new save first waits out the previous
  commit (back-pressure) so checkpoints are totally ordered on disk;
- the writer thread never touches the engine or any device array — it
  owns an immutable host snapshot, so training may mutate state freely;
- commits are crash-consistent (staging dir + checksum manifest + atomic
  renames, `manifest.py`) and `latest` only ever names a fully-committed
  checkpoint;
- retention GC (`keep_last_n` / `keep_every_n_steps`) runs after each
  commit, deletes committed checkpoints only, and never the one `latest`
  points to;
- writer failures are captured and re-raised on the main thread at the
  next `wait()` / save (and logged at the next step boundary) — a broken
  disk is loud, not silent.
"""

import atexit
import signal
import threading
import time

import jax

from ..runtime.utils import register_weak_atexit
from ..utils.logging import log_dist, logger
from . import manifest as mf


class AsyncCheckpointManager:
    """Engine-attached manager for async saves, auto-save, retention and
    preemption handling. Constructed by the engine from the "checkpoint"
    config block; usable directly for ad-hoc async saves."""

    def __init__(self, engine, save_dir=None, async_save=True,
                 save_interval_steps=0, keep_last_n=0,
                 keep_every_n_steps=0, save_on_preemption=False):
        self.engine = engine
        self.save_dir = save_dir
        self.async_save = bool(async_save)
        self.save_interval_steps = int(save_interval_steps or 0)
        self.keep_last_n = int(keep_last_n or 0)
        self.keep_every_n_steps = int(keep_every_n_steps or 0)
        self.save_on_preemption = bool(save_on_preemption)

        self._thread = None
        self._inflight_tag = None
        self._error = None
        self._error_logged = False
        self._warned_sync_multihost = False
        self._warned_sync_streamed = False
        self._lock = threading.Lock()
        self._finished = []         # per-save stats awaiting monitor drain
        self._last_autosave_step = 0   # first auto-save after one interval
        self._prev_handlers = {}
        self.preemption_requested = False
        self._preempt_signum = None
        # test seam: runs inside the writer thread before the commit
        self._pre_commit_hook = None

        # goodput counters (cumulative, host-side)
        self.saves_completed = 0
        self.total_stall_s = 0.0    # training blocked in snapshot
        self.total_write_s = 0.0    # background serialization + commit
        self.total_bytes = 0

        if self.save_on_preemption:
            self._install_signal_handlers()
        # flush an in-flight commit at interpreter exit — a clean shutdown
        # must never lose an already-snapshotted checkpoint. Weakly held:
        # the registry must not pin the manager (and through it the whole
        # engine); discarded engines stay collectible.
        self._atexit = register_weak_atexit(self, "_drain_at_exit")

    # ------------------------------------------------------------------
    # save API
    # ------------------------------------------------------------------

    @property
    def in_flight(self):
        return self._thread is not None and self._thread.is_alive()

    def wait(self):
        """Block until the in-flight commit (if any) finishes; re-raise a
        writer failure on the caller's thread."""
        thread = self._thread
        if thread is not None:
            thread.join()
            self._thread = None
            self._inflight_tag = None
        with self._lock:
            err, self._error = self._error, None
            self._error_logged = False
        if err is not None:
            from ..elasticity.config import PeerFailureError
            if isinstance(err, PeerFailureError):
                # a commit-barrier timeout on a missing peer: the typed
                # error (and its supervisor-recognized exit code 76)
                # must survive the thread handoff — wrapping it in
                # RuntimeError would demote restartable peer loss to a
                # generic crash
                raise err
            raise RuntimeError(
                f"async checkpoint save failed: {err}") from err

    def save_async(self, save_dir=None, tag=None, client_state=None,
                   save_latest=True):
        """Snapshot now, commit in the background. Returns the tag once
        the snapshot is taken (training may resume); the checkpoint is on
        disk only after the commit — `wait()` for durability."""
        from .checkpointing import snapshot_checkpoint, write_and_commit

        engine = self.engine
        save_dir = save_dir if save_dir is not None else self.save_dir
        if save_dir is None:
            raise ValueError("save_async needs a save_dir (argument or "
                             "the checkpoint.save_dir config key)")
        if tag is None:
            tag = f"global_step{engine.global_steps}"
        tag = str(tag)

        # back-pressure: one save in flight, totally ordered commits
        self.wait()

        if getattr(engine, "_grad_spill", None) is not None:
            # Streamed-NVMe store of record: its checkpoint IS the live
            # segment files (no host snapshot exists to hand a writer
            # thread). Auto-save/preemption must still produce a
            # checkpoint — route through the tier's own sync save.
            from .checkpointing import save_checkpoint
            if not self._warned_sync_streamed:
                self._warned_sync_streamed = True
                logger.warning("async checkpoint save degrades to the "
                               "synchronous streamed-NVMe path on the "
                               "store-of-record tier")
            save_checkpoint(engine, save_dir, tag=tag,
                            client_state=client_state,
                            save_latest=save_latest)
            return tag

        # cross-host tag agreement is checked on the calling thread,
        # before the snapshot: the KV compare must never ride the
        # writer thread (the same rule as the commit barrier), and a
        # FAIL-mode mismatch must abort before any stall is paid
        # (the streamed-NVMe branch above validates inside its sync
        # save_checkpoint instead)
        from .checkpointing import _validate_checkpoint_tag
        _validate_checkpoint_tag(engine, tag)

        from ..runtime.telemetry import NULL_TELEMETRY
        telemetry = getattr(engine, "telemetry", NULL_TELEMETRY)
        t0 = time.perf_counter()
        # the snapshot is the only training-loop stall of an async save;
        # spanning it puts the stall on the trace timeline AND lets the
        # goodput meter see mid-step saves (the ckpt_stall bucket reads
        # total_stall_s deltas per step window)
        with telemetry.span("ckpt_snapshot"):
            payloads = snapshot_checkpoint(engine, client_state)
        stall_s = time.perf_counter() - t0
        step = engine.global_steps
        self.total_stall_s += stall_s

        def writer():
            # (runs on the calling thread under multihost — see below)
            try:
                if self._pre_commit_hook is not None:
                    self._pre_commit_hook()
                t1 = time.perf_counter()
                nbytes = write_and_commit(payloads, save_dir, tag,
                                          step=step,
                                          save_latest=save_latest)
                if jax.process_index() == 0:
                    deleted = mf.gc_checkpoints(
                        save_dir, keep_last_n=self.keep_last_n,
                        keep_every_n_steps=self.keep_every_n_steps,
                        protect=(tag,))
                else:
                    deleted = []
                write_s = time.perf_counter() - t1
                with self._lock:
                    self.saves_completed += 1
                    self.total_write_s += write_s
                    self.total_bytes += nbytes
                    self._finished.append({
                        "tag": tag, "step": step, "bytes": nbytes,
                        "stall_s": stall_s, "write_s": write_s,
                        "deleted": deleted})
            except BaseException as e:  # surfaced at the next wait()
                with self._lock:
                    self._error = e

        if jax.process_count() > 1:
            # The commit barrier is a DEVICE collective; enqueueing it
            # from a writer thread can interleave differently with the
            # main thread's train-step collectives on different hosts —
            # a distributed deadlock. Until commits coordinate over a
            # host-side channel, multihost saves commit inline (the
            # snapshot/serialization split still bounds the stall
            # structure, and single-host async is unaffected).
            if not self._warned_sync_multihost:
                self._warned_sync_multihost = True
                logger.warning(
                    "async checkpoint commit degrades to inline under "
                    "multihost (device-collective barrier must stay on "
                    "the main thread)")
            writer()
            err = None
            with self._lock:
                err, self._error = self._error, None
            if err is not None:
                from ..elasticity.config import PeerFailureError
                if isinstance(err, PeerFailureError):
                    raise err   # keep the typed exit-76 peer failure
                raise RuntimeError(
                    f"checkpoint save failed: {err}") from err
            return tag

        thread = threading.Thread(target=writer, daemon=True,
                                  name=f"ds-ckpt-writer-{tag}")
        self._thread = thread
        self._inflight_tag = tag
        thread.start()
        return tag

    def save_sync(self, save_dir=None, tag=None, client_state=None,
                  save_latest=True):
        """The same snapshot-then-commit protocol, waited to completion
        before returning (emergency saves, final saves)."""
        tag = self.save_async(save_dir, tag=tag, client_state=client_state,
                              save_latest=save_latest)
        self.wait()
        return tag

    # ------------------------------------------------------------------
    # engine hooks (called at every step boundary)
    # ------------------------------------------------------------------

    def on_step_boundary(self, engine):
        """Drain completed-save telemetry, honor a pending preemption
        request, and fire the auto-save interval."""
        self._drain_finished(engine)
        if self.preemption_requested:
            self._emergency_save(engine)   # raises to stop the loop
            return
        if (self.save_interval_steps and self.save_dir
                and engine.global_steps - self._last_autosave_step
                >= self.save_interval_steps):
            # interval-CROSSING test, not an exact modulo: train_steps
            # windows advance global_steps by n_steps per boundary and
            # fp16 overflows shift the phase — `% interval == 0` could
            # land rarely or never. (It also keeps an overflow re-entry
            # at an unchanged global step from double-saving.)
            self._last_autosave_step = engine.global_steps
            if self.async_save:
                self.save_async(self.save_dir)
            else:
                self.save_sync(self.save_dir)

    def on_checkpoint_loaded(self, engine):
        """Re-sync the auto-save clock after a resume: global_steps just
        jumped to the restored value, and the interval-crossing test
        would otherwise fire a (near-duplicate) save on the very first
        post-resume step."""
        self._last_autosave_step = engine.global_steps

    def _drain_finished(self, engine):
        with self._lock:
            finished, self._finished = self._finished, []
            err = self._error
        for stats in finished:
            log_dist(
                f"Committed checkpoint {stats['tag']} "
                f"({stats['bytes'] / 2**20:.1f} MiB, "
                f"stall {stats['stall_s'] * 1e3:.0f} ms, "
                f"write {stats['write_s'] * 1e3:.0f} ms"
                + (f", GC'd {stats['deleted']}" if stats["deleted"]
                   else "") + ")", ranks=[0])
            monitor = getattr(engine, "monitor", None)
            if monitor is not None:
                monitor.record_checkpoint(engine.global_samples, stats)
        if err is not None and not self._error_logged:
            # keep self._error for wait() to raise; warn NOW (once) so a
            # dead disk surfaces even in fire-and-forget training loops
            self._error_logged = True
            logger.error(f"async checkpoint writer failed: {err}")

    # ------------------------------------------------------------------
    # preemption (SIGTERM from the TPU scheduler, SIGINT from a human)
    # ------------------------------------------------------------------

    def _install_signal_handlers(self):
        if threading.current_thread() is not threading.main_thread():
            logger.warning("save_on_preemption: not on the main thread; "
                           "signal handlers not installed")
            return
        # weakly bound, like the atexit hook: the signal registry (and a
        # successor manager's saved prev-handler chain) must not pin this
        # manager and its engine for the process lifetime
        import weakref
        manager_ref = weakref.ref(self)

        def handler(signum, frame):
            manager = manager_ref()
            if manager is not None:
                manager._on_signal(signum, frame)

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass

    def _on_signal(self, signum, frame):  # noqa: ARG002
        # async-signal-safe: only flip flags here; the actual save runs
        # on the main thread at the next step boundary (mid-step device
        # state is not a consistent snapshot)
        self.preemption_requested = True
        self._preempt_signum = signum

    def restore_signal_handlers(self):
        for sig, handler in self._prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev_handlers = {}

    def _emergency_save(self, engine):
        signum = self._preempt_signum
        self.preemption_requested = False
        log_dist(f"Preemption signal {signum}: saving emergency "
                 f"checkpoint at step {engine.global_steps}", ranks=[0])
        self.save_sync(self.save_dir)
        self.restore_signal_handlers()
        # surface the interruption to the training loop with the
        # conventional exception for the signal
        if signum == signal.SIGINT:
            raise KeyboardInterrupt("preemption checkpoint saved")
        raise SystemExit(128 + int(signum or signal.SIGTERM))

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def _drain_at_exit(self):  # pragma: no cover - interpreter teardown
        try:
            self.wait()
        except Exception as e:
            logger.error(f"checkpoint writer failed during shutdown: {e}")

    def close(self):
        """Flush the in-flight save and detach signal/atexit hooks."""
        try:
            self.wait()
        finally:
            self.restore_signal_handlers()
            try:
                atexit.unregister(self._atexit)
            except Exception:  # pragma: no cover
                pass
