__version__ = "0.3.15"
__version_major__ = 0
__version_minor__ = 3
__version_patch__ = 15
# TPU-native rebuild generation; bumped per round.
__tpu_build__ = 1
