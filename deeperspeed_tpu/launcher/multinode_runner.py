"""Multinode launcher backends (reference:
`deepspeed/launcher/multinode_runner.py`): pdsh, OpenMPI, MVAPICH, Slurm
(srun, fork addition) and MosaicML (fork addition).

Each runner constructs the command line that starts the per-node launcher
(`deeperspeed_tpu.launcher.launch`) on every host. One process per host
(JAX addresses all local chips); the per-process env carries the
jax.distributed rendezvous.
"""

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import split

from ..utils.logging import logger


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64=None):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    def add_export(self, key, var):
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args

    @property
    def name(self):
        return self.__class__.__name__


class PDSHRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64=None):
        super().__init__(args, world_info_base64)

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        logger.info(f"Running on the following workers: {active_workers}")

        pdsh_cmd = ["pdsh", "-f", "1024", "-w", active_workers]
        exports = ""
        for key, val in self.exports.items():
            exports += f"export {key}={val}; "

        from .launch import elastic_argv
        from .runner import encode_world_info
        world_info = encode_world_info(dict(active_resources))
        deepspeed_launch = [
            exports, f"cd {os.path.abspath('.')};",
            sys.executable, "-u", "-m",
            "deeperspeed_tpu.launcher.launch",
            f"--world_info={world_info}",
            "--node_rank=%n",
            f"--master_addr={environment['MASTER_ADDR']}",
            f"--master_port={environment['MASTER_PORT']}",
        ]
        # per-node supervised restarts (--elastic and friends) ride the
        # same pass-through as the rendezvous flags
        deepspeed_launch += elastic_argv(self.args)
        return pdsh_cmd + deepspeed_launch + [self.user_script] + \
            self.user_arguments


class OpenMPIRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64=None, resource_pool=None):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self):
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        total_processes = len(active_resources)  # one process per host
        mpirun_cmd = [
            "mpirun", "-n", f"{total_processes}",
            "-hostfile", self.args.hostfile,
            "--mca", "btl", "^openib",
            "--mca", "btl_tcp_if_include", "eth0",
        ] + split(self.args.launcher_args)
        export_cmd = []
        for key, val in self.exports.items():
            export_cmd += ["-x", f"{key}={val}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + \
            [self.user_script] + self.user_arguments


class MVAPICHRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64=None, resource_pool=None):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        # TPU hosts talk over standard TCP/IP; MVAPICH's InfiniBand-specific
        # tuning from the reference is irrelevant here.
        self.add_export("MV2_SMP_USE_CMA", "0")
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")

    def backend_exists(self):
        mpiname = shutil.which("mpiname")
        if mpiname is None:
            logger.warning("mpiname does not exist")
            return False
        import subprocess
        results = subprocess.check_output(["mpiname"]).decode("utf-8")
        return "MVAPICH2-GDR" in results or "MVAPICH" in results

    def get_cmd(self, environment, active_resources):
        devices_per_node = active_resources.values()
        total_process_count = len(active_resources)
        process_per_node = 1
        if len(set(devices_per_node)) != 1:
            logger.warning("mvapich requires same number of chips per node")

        with open("hostfile", "w") as fd:
            for host in active_resources.keys():
                fd.write(f"{host}:{process_per_node}\n")

        mpirun_cmd = [
            "mpirun", "-np", f"{total_process_count}",
            "-ppn", f"{process_per_node}",
            "--hostfile", "hostfile",
        ] + split(self.args.launcher_args)
        export_cmd = []
        for key, val in self.exports.items():
            export_cmd += ["-env", f"{key}={val}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + \
            [self.user_script] + self.user_arguments


class SlurmRunner(MultiNodeRunner):
    """srun-based launcher (fork addition: reference
    `multinode_runner.py:124`, incl. `--comment` passthrough)."""

    def __init__(self, args, world_info_base64=None, resource_pool=None):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self):
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment, active_resources):
        assert not getattr(self.args, "detect_nvlink_pairs", False), \
            "slurm backend does not support remapping visible devices"
        total_process_count = len(active_resources)
        srun_cmd = [
            "srun", "-n", f"{total_process_count}",
        ] + split(self.args.launcher_args)

        if getattr(self.args, "include", ""):
            srun_cmd.append("--include")
            srun_cmd.append(f"{self.args.include}")
        if getattr(self.args, "exclude", ""):
            srun_cmd.append("--exclude")
            srun_cmd.append(f"{self.args.exclude}")
        if getattr(self.args, "num_nodes", -1) > 0:
            srun_cmd.append("--nodes")
            srun_cmd.append(f"{self.args.num_nodes}")
        if getattr(self.args, "comment", ""):
            srun_cmd.append("--comment")
            srun_cmd.append(f"{self.args.comment}")

        exports = ""
        for key, val in self.exports.items():
            exports += f"{key}={val},"
        if exports:
            srun_cmd += ["--export", exports.rstrip(",")]

        python_exec = [sys.executable, "-u"]
        return srun_cmd + python_exec + [self.user_script] + \
            self.user_arguments


class MosaicMLRunner(MultiNodeRunner):
    """MosaicML platform launcher (fork addition: reference
    `multinode_runner.py:256`); rendezvous comes from the platform's env."""

    def __init__(self, args, world_info_base64=None, resource_pool=None):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self):
        return os.environ.get("MOSAICML_PLATFORM", "") != ""

    def get_cmd(self, environment, active_resources):
        python_exec = [sys.executable, "-u", "-m",
                       "deeperspeed_tpu.launcher.launch"]
        from .runner import encode_world_info
        world_info = encode_world_info(dict(active_resources))
        launch_args = [
            f"--world_info={world_info}",
            f"--node_rank={os.environ.get('NODE_RANK', '0')}",
            f"--master_addr={environment['MASTER_ADDR']}",
            f"--master_port={environment['MASTER_PORT']}",
        ]
        return python_exec + launch_args + [self.user_script] + \
            self.user_arguments
