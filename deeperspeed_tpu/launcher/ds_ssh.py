"""``ds_ssh``: run a shell command on every host of a hostfile over ssh
(capability of reference `bin/ds_ssh`). On TPU pods the hostfile lists the
TPU-VM workers; this is the quick "fan a command across the pod" helper.
"""

import argparse
import shlex
import subprocess
import sys

from .runner import fetch_hostfile

DEFAULT_HOSTFILE = "/job/hostfile"


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Run a command on all hosts in a hostfile via ssh")
    parser.add_argument("-f", "--hostfile", default=DEFAULT_HOSTFILE)
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on every host")
    args = parser.parse_args(argv)

    if not args.command:
        parser.error("no command given")
    cmd = shlex.join(args.command)

    resources = fetch_hostfile(args.hostfile)
    if not resources:
        print(f"No hosts found in {args.hostfile}", file=sys.stderr)
        return 1

    procs = []
    for host in resources:
        procs.append((host, subprocess.Popen(["ssh", host, cmd])))
    rc = 0
    for host, proc in procs:
        code = proc.wait()
        if code != 0:
            print(f"[{host}] exited with {code}", file=sys.stderr)
            rc = rc or code
    return rc


if __name__ == "__main__":
    sys.exit(main())
