"""Per-node launcher (reference: `deepspeed/launcher/launch.py:69`).

The reference spawns one subprocess per local GPU rank with
RANK/LOCAL_RANK/WORLD_SIZE/MASTER_* env vars. On TPU one process drives
every local chip, so this spawns ONE subprocess per node (rank ==
node_rank) and exports the jax.distributed rendezvous env; ``DS_SLOTS``
carries the chip count for the hostfile's slots= entry. Signal handling
matches the reference: SIGINT/SIGTERM kill the child process group.

Elastic mode (``--elastic``): the child runs under an
`elasticity.supervisor.Supervisor` — restartable failures (peer death,
preemption, crash) relaunch it with capped exponential backoff inside a
restart budget, and the poison-step detector aborts a deterministic
crash loop. The supervisor's state dir (progress + restart records) is
exported to the child as ``DS_ELASTIC_STATE_DIR``.
"""

import argparse
import os
import signal
import subprocess
import sys

from ..elasticity import constants as ec
from ..elasticity.supervisor import Supervisor
from ..utils.logging import logger
from .runner import decode_world_info


def add_elastic_args(parser):
    """The supervised-restart CLI surface, shared by the `deepspeed`
    front-end (`runner.py`, which forwards them here) and this per-node
    launcher. Numeric defaults are None so resolution can tell "flag
    given" from "flag omitted": explicit CLI > the config's
    `elasticity.supervisor` block > built-in defaults."""
    parser.add_argument("--elastic", action="store_true",
                        help="supervise the training process: restart "
                        "restartable failures with backoff + budget "
                        "(also enabled by elasticity.supervisor.enabled "
                        "in the ds config)")
    parser.add_argument("--elastic_state_dir", type=str, default=None,
                        help="dir for progress/restart records (exported "
                        "to the child as DS_ELASTIC_STATE_DIR; default "
                        ".ds_elastic)")
    parser.add_argument("--elastic_max_restarts", type=int, default=None)
    parser.add_argument("--elastic_backoff_base_s", type=float,
                        default=None)
    parser.add_argument("--elastic_backoff_max_s", type=float,
                        default=None)
    parser.add_argument("--elastic_backoff_jitter", type=float,
                        default=None)
    parser.add_argument("--elastic_poison_step_threshold", type=int,
                        default=None)


_ELASTIC_FLAGS = ("elastic_state_dir", "elastic_max_restarts",
                  "elastic_backoff_base_s", "elastic_backoff_max_s",
                  "elastic_backoff_jitter",
                  "elastic_poison_step_threshold")


def elastic_argv(args):
    """Re-serialize the elastic flags for forwarding to launch.py
    (only the ones actually given — omitted flags stay resolvable from
    the config block on the receiving side)."""
    if not getattr(args, "elastic", False):
        return []
    out = ["--elastic"]
    for flag in _ELASTIC_FLAGS:
        value = getattr(args, flag, None)
        if value is not None:
            out += [f"--{flag}", str(value)]
    return out


def _find_ds_config(user_args):
    """The ds-config JSON path from the user script's own args (the
    launcher forwards them verbatim, so the `elasticity.supervisor`
    policy block can be honored without a second config mechanism)."""
    for i, arg in enumerate(user_args):
        if arg in ("--deepspeed_config", "--deepspeed-config"):
            if i + 1 < len(user_args):
                return user_args[i + 1]
        for prefix in ("--deepspeed_config=", "--deepspeed-config="):
            if arg.startswith(prefix):
                return arg[len(prefix):]
    return None


def resolve_supervisor_params(args):
    """(enabled, params) for the restart supervisor: explicit CLI flags
    override the ds config's `elasticity.supervisor` block, which
    overrides built-in defaults. Supervision is on when `--elastic` was
    given OR the block says `enabled: true`. A malformed block raises
    here (parse-time strictness — same error the engine would raise,
    but before any process is spawned)."""
    import json

    block = False
    config_path = _find_ds_config(args.user_args)
    if config_path:
        try:
            with open(config_path) as f:
                config = json.load(f)
        except (OSError, ValueError) as e:
            # unreadable config: the CHILD will fail with the real
            # error; don't duplicate it here
            logger.warning(f"could not read {config_path} for the "
                           f"elasticity.supervisor block ({e})")
            config = {}
        from ..elasticity.config import parse_supervisor_block
        block = parse_supervisor_block(
            (config.get(ec.ELASTICITY) or {}).get(ec.SUPERVISOR))
    enabled = bool(getattr(args, "elastic", False)) or bool(block)

    def pick(cli_value, key, default):
        if cli_value is not None:
            return cli_value
        if block and key in block:
            return block[key]
        return default

    params = {
        "state_dir": pick(args.elastic_state_dir, None, ".ds_elastic"),
        "max_restarts": pick(args.elastic_max_restarts,
                             "max_restarts",
                             ec.SUPERVISOR_MAX_RESTARTS_DEFAULT),
        "backoff_base_s": pick(args.elastic_backoff_base_s,
                               "backoff_base_s",
                               ec.SUPERVISOR_BACKOFF_BASE_DEFAULT),
        "backoff_max_s": pick(args.elastic_backoff_max_s,
                              "backoff_max_s",
                              ec.SUPERVISOR_BACKOFF_MAX_DEFAULT),
        "backoff_jitter": pick(args.elastic_backoff_jitter,
                               "backoff_jitter",
                               ec.SUPERVISOR_BACKOFF_JITTER_DEFAULT),
        "poison_step_threshold": pick(
            args.elastic_poison_step_threshold, "poison_step_threshold",
            ec.SUPERVISOR_POISON_STEP_THRESHOLD_DEFAULT),
    }
    return enabled, params


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeeperSpeed-TPU per-node launcher")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--world_info", type=str, default="None",
                        help="base64-encoded {hostname: slots} dict")
    add_elastic_args(parser)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def main(args=None):
    args = parse_args(args)

    if args.world_info == "None":
        world_info = {"localhost": None}
    else:
        world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    world_size = len(hosts)
    node_rank = args.node_rank
    slots = world_info[hosts[min(node_rank, world_size - 1)]]

    env = dict(os.environ)
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(world_size)
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)
    env["NODE_RANK"] = str(node_rank)
    if slots is not None:
        env["DS_SLOTS"] = str(slots)

    cmd = [sys.executable, "-u", args.user_script] + args.user_args

    elastic_enabled, sup_params = resolve_supervisor_params(args)
    if elastic_enabled:
        return _run_supervised(sup_params, cmd, env, node_rank,
                               world_size)

    logger.info(f"launching: {' '.join(cmd)} (rank {node_rank}/"
                f"{world_size})")
    process = subprocess.Popen(cmd, env=env)

    def sig_handler(signum, frame):
        logger.info(f"Received signal {signum}, killing child "
                    f"{process.pid}")
        try:
            process.terminate()
        except OSError:
            pass
        sys.exit(1)

    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)

    process.wait()
    if process.returncode != 0:
        sys.exit(process.returncode)


def _run_supervised(sup_params, cmd, env, node_rank, world_size):
    """Elastic path: the child runs under the restart supervisor; a
    launcher-level SIGTERM/SIGINT stops the restart loop AND the child
    (a real shutdown must not be "restarted")."""
    state_dir = os.path.join(sup_params["state_dir"], f"rank{node_rank}")
    supervisor = Supervisor(
        cmd, state_dir, env=env,
        max_restarts=sup_params["max_restarts"],
        backoff_base_s=sup_params["backoff_base_s"],
        backoff_max_s=sup_params["backoff_max_s"],
        backoff_jitter=sup_params["backoff_jitter"],
        poison_step_threshold=sup_params["poison_step_threshold"])

    def sig_handler(signum, frame):
        logger.info(f"Received signal {signum}: stopping supervised "
                    "child and the restart loop")
        supervisor.terminate_child()

    prev_handlers = {
        sig: signal.signal(sig, sig_handler)
        for sig in (signal.SIGINT, signal.SIGTERM)}

    logger.info(f"launching under supervision: {' '.join(cmd)} "
                f"(rank {node_rank}/{world_size}, "
                f"budget {sup_params['max_restarts']} restarts, "
                f"state {state_dir})")
    try:
        stats = supervisor.run()
    finally:
        # restore on the way out: an embedding caller (tests, a driver
        # script) must not inherit a handler bound to a dead supervisor
        for sig, handler in prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
    if stats["restarts"]:
        logger.info(f"supervisor stats: {stats}")
    if stats["exit_code"] != 0:
        sys.exit(stats["exit_code"])


if __name__ == "__main__":
    main()
