"""Per-node launcher (reference: `deepspeed/launcher/launch.py:69`).

The reference spawns one subprocess per local GPU rank with
RANK/LOCAL_RANK/WORLD_SIZE/MASTER_* env vars. On TPU one process drives
every local chip, so this spawns ONE subprocess per node (rank ==
node_rank) and exports the jax.distributed rendezvous env; ``DS_SLOTS``
carries the chip count for the hostfile's slots= entry. Signal handling
matches the reference: SIGINT/SIGTERM kill the child process group.
"""

import argparse
import os
import signal
import subprocess
import sys

from ..utils.logging import logger
from .runner import decode_world_info


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeeperSpeed-TPU per-node launcher")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--world_info", type=str, default="None",
                        help="base64-encoded {hostname: slots} dict")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def main(args=None):
    args = parse_args(args)

    if args.world_info == "None":
        world_info = {"localhost": None}
    else:
        world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    world_size = len(hosts)
    node_rank = args.node_rank
    slots = world_info[hosts[min(node_rank, world_size - 1)]]

    env = dict(os.environ)
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(world_size)
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)
    env["NODE_RANK"] = str(node_rank)
    if slots is not None:
        env["DS_SLOTS"] = str(slots)

    cmd = [sys.executable, "-u", args.user_script] + args.user_args
    logger.info(f"launching: {' '.join(cmd)} (rank {node_rank}/"
                f"{world_size})")
    process = subprocess.Popen(cmd, env=env)

    def sig_handler(signum, frame):
        logger.info(f"Received signal {signum}, killing child "
                    f"{process.pid}")
        try:
            process.terminate()
        except OSError:
            pass
        sys.exit(1)

    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)

    process.wait()
    if process.returncode != 0:
        sys.exit(process.returncode)


if __name__ == "__main__":
    main()
