"""`deepspeed` CLI launcher (reference: `deepspeed/launcher/runner.py`).

Same front-end contract: hostfile with ``hostname slots=N`` lines,
``--include``/``--exclude`` resource filters, base64 world-info handoff,
and a pluggable multinode backend (pdsh / OpenMPI / MVAPICH / Slurm /
MosaicML — the fork's additions included).

TPU semantics: a "slot" is a chip; the launcher starts ONE process per
host (JAX addresses all local chips from one process) and exports
``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/``MASTER_PORT`` for
`jax.distributed.initialize` plus ``DS_SLOTS`` with the chip count. On
TPU pods the pod runtime usually launches processes itself — then this CLI
degenerates to the single-node exec path.
"""

import argparse
import base64
import collections
import json
import os
import subprocess
import sys
from copy import deepcopy

from ..utils.logging import logger
from .multinode_runner import (MosaicMLRunner, MVAPICHRunner, OpenMPIRunner,
                               PDSHRunner, SlurmRunner)

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["JAX", "XLA", "TPU", "PYTHON", "PATH", "LD_LIBRARY"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = [".", os.path.expanduser("~")]
PDSH_MAX_FAN_OUT = 1024


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeeperSpeed-TPU distributed launcher")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path: lines of 'hostname slots=N'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Resources to include: "
                        "NODE_SPEC[@NODE_SPEC ...], NODE_SPEC = "
                        "NAME[:SLOT[,SLOT ...]]")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Resources to exclude (same syntax)")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        help="pdsh | openmpi | mvapich | slurm | mosaicml")
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--comment", type=str, default="",
                        help="Run comment passed to the Slurm launcher "
                        "(fork addition)")
    parser.add_argument("--detect_nvlink_pairs", action="store_true",
                        help="Accepted for CLI compat; no-op on TPU "
                        "(ICI topology is fixed)")
    # Supervised-restart flags (forwarded to the per-node launcher):
    # --elastic wraps each node's training process in the
    # elasticity.supervisor restart loop (backoff + budget + poison-step
    # detection).
    from .launch import add_elastic_args
    add_elastic_args(parser)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse ``hostname slots=N`` lines → OrderedDict[host] = slots."""
    if not os.path.isfile(hostfile_path):
        logger.warning("Unable to find hostfile, will proceed with training "
                       "with local resources only.")
        return None
    resource_pool = collections.OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd:
            line = line.strip()
            if not line:
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                logger.error("Hostfile is not formatted correctly, unable "
                             "to proceed with training.")
                raise
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Filter a hostfile dict by include/exclude strings
    (NODE_SPEC[@NODE_SPEC ...], NODE_SPEC = NAME[:SLOT[,SLOT ...]])."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually "
                         "exclusive.")
    if not include_str and not exclude_str:
        return host_info

    filtered_hosts = {}
    if include_str:
        parse_str = include_str
    else:
        filtered_hosts = deepcopy(host_info)
        parse_str = exclude_str

    for node_config in parse_str.split("@"):
        if ":" in node_config:
            hostname, slots = node_config.split(":")
            slots = [int(x) for x in slots.split(",")]
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in "
                                 "hostfile")
            for slot in slots:
                if slot >= host_info[hostname]:
                    raise ValueError(f"No slot '{slot}' specified on host "
                                     f"'{hostname}'")
            if include_str:
                filtered_hosts.setdefault(hostname, 0)
                filtered_hosts[hostname] += len(slots)
            else:
                filtered_hosts[hostname] -= len(slots)
                if filtered_hosts[hostname] <= 0:
                    del filtered_hosts[hostname]
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in "
                                 "hostfile")
            if include_str:
                filtered_hosts[hostname] = host_info[hostname]
            elif hostname in filtered_hosts:
                del filtered_hosts[hostname]

    ordered = collections.OrderedDict(
        (host, filtered_hosts[host]) for host in host_info
        if host in filtered_hosts)
    return ordered


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    active_resources = collections.OrderedDict()
    for hostname, slots in resource_pool.items():
        active_resources[hostname] = slots
    return parse_resource_filter(active_resources, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info):
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded))


def _ds_env_exports():
    """Collect extra env exports from a .deepspeed_env file."""
    exports = {}
    for path in DEEPSPEED_ENVIRONMENT_PATHS:
        env_file = os.path.join(path, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(env_file):
            with open(env_file) as f:
                for line in f:
                    line = line.strip()
                    if line and "=" in line:
                        key, val = line.split("=", 1)
                        exports[key] = val
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool:
        # Single node: exec the per-node launcher in-process.
        from .launch import elastic_argv, main as launch_main
        world_info = {"localhost": args.num_gpus if args.num_gpus > 0
                      else None}
        encoded = encode_world_info(world_info)
        argv = ["--world_info", encoded,
                "--master_port", str(args.master_port)] + \
            elastic_argv(args) + [args.user_script] + args.user_args
        return launch_main(argv)

    active_resources = parse_inclusion_exclusion(resource_pool,
                                                 args.include, args.exclude)
    if args.num_nodes > 0:
        active_resources = collections.OrderedDict(
            list(active_resources.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active_resources = collections.OrderedDict(
            (k, min(v, args.num_gpus)) for k, v in active_resources.items())

    master_addr = args.master_addr or list(active_resources.keys())[0]

    runners = {
        "pdsh": PDSHRunner,
        "openmpi": OpenMPIRunner,
        "mvapich": MVAPICHRunner,
        "slurm": SlurmRunner,
        "mosaicml": MosaicMLRunner,
    }
    if args.launcher.lower() not in runners:
        raise NotImplementedError(
            f"Unknown launcher {args.launcher}; valid: "
            f"{sorted(runners)}")
    from .launch import resolve_supervisor_params
    elastic_enabled, _ = resolve_supervisor_params(args)
    if elastic_enabled and args.launcher.lower() != "pdsh":
        # the MPI/Slurm/MosaicML backends exec the training script
        # directly (no per-node launch.py to wrap in the supervisor);
        # silently launching WITHOUT restart supervision would be
        # discovered only at the first unrecovered preemption
        raise NotImplementedError(
            f"--elastic supervised restarts are only forwarded by the "
            f"pdsh backend; launcher '{args.launcher}' execs the "
            f"training script directly. Wrap each node's command "
            f"explicitly instead: python -m "
            f"deeperspeed_tpu.elasticity.supervisor --state_dir DIR "
            f"-- <training cmd>")
    runner = runners[args.launcher.lower()](args, active_resources)
    if not runner.backend_exists():
        raise RuntimeError(
            f"launcher backend '{args.launcher}' not installed")

    world_info = encode_world_info(dict(active_resources))
    env = dict(os.environ)
    env.update(_ds_env_exports())
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(args.master_port)

    cmd = runner.get_cmd(env, active_resources)
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    if result.returncode > 0:
        sys.exit(result.returncode)


if __name__ == "__main__":
    main()
