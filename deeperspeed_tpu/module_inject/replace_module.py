"""Model surgery: swap HuggingFace/Megatron BERT-style layers for the
fused `DeepSpeedTransformerLayer` (reference:
`deepspeed/module_inject/replace_module.py:5`, `inject.py`).

The reference mutates a torch model in place, copying each `BertLayer`'s
weights into the fused CUDA layer. Here the torch model is the *source*:
weights are extracted host-side into the TPU layer's parameter pytree, and
the result is a (layers, params, apply_fn) triple that runs the whole
encoder stack as one jittable function.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)


def prepare_inference_params(params, dtype, weight_quant=None):
    """Inference-side module surgery for the serving engine: pre-cast
    every matmul weight (ndim >= 2) of a parameter pytree to the serving
    compute dtype ONCE at load, keeping 1-D leaves (layernorm scales/
    biases, projection biases) in fp32 for accumulation quality.

    This is the TPU analogue of what `replace_transformer_layer` does
    for torch models: the reference copies weights into fused
    inference kernels at injection time; here the block body's
    per-call ``.astype(x.dtype)`` becomes an XLA no-op because the
    weights already REST in the compute dtype — no per-step cast
    traffic, half the weight HBM at bf16.

    ``weight_quant="int8"`` (the ``quantization.weights`` config choice)
    additionally converts the BLOCK matmul weights (ndim >= 2 leaves
    under ``params["blocks"]``) to `QuantizedWeight` — int8 at rest with
    per-output-channel fp32 scales, dequantized inside the matmul kernel
    (`ops/pallas/quant_matmul`). Decode is weight-bandwidth bound, so
    int8 weights halve the bytes every decode step streams. The
    embedding / LM head / final-norm leaves stay at the compute dtype
    (the embedding doubles as a gather table and, tied, as the head)."""
    def cast(leaf):
        if getattr(leaf, "ndim", 0) >= 2:
            return jnp.asarray(leaf, dtype)
        return jnp.asarray(leaf, jnp.float32)

    out = jax.tree_util.tree_map(cast, params)
    if weight_quant is None:
        return out
    if weight_quant != "int8":
        raise ValueError(
            f"weight_quant must be None or 'int8', got {weight_quant!r}")
    if not (isinstance(out, dict) and "blocks" in out):
        raise ValueError(
            "weight_quant='int8' quantizes the block matmul weights and "
            "needs a params tree with a 'blocks' entry (the GPT-NeoX / "
            "GPT-2 family layout)")
    from ..ops.pallas.quant_matmul import quantize_weight

    def quant(leaf):
        if getattr(leaf, "ndim", 0) >= 2:
            return quantize_weight(leaf)
        return leaf

    out = dict(out)
    out["blocks"] = [jax.tree_util.tree_map(quant, b)
                     for b in out["blocks"]]
    return out


def _t(x):
    return np.asarray(x.detach().cpu().numpy() if hasattr(x, "detach")
                      else x)


def extract_bert_layer_params(bert_layer):
    """HF `BertLayer` → DeepSpeedTransformerLayer parameter dict."""
    attn = bert_layer.attention
    selfattn = attn.self
    qkv_w = np.concatenate([
        _t(selfattn.query.weight).T,
        _t(selfattn.key.weight).T,
        _t(selfattn.value.weight).T,
    ], axis=1)
    qkv_b = np.concatenate([
        _t(selfattn.query.bias),
        _t(selfattn.key.bias),
        _t(selfattn.value.bias),
    ])
    return {
        "attn_qkvw": jnp.asarray(qkv_w),
        "attn_qkvb": jnp.asarray(qkv_b),
        "attn_ow": jnp.asarray(_t(attn.output.dense.weight).T),
        "attn_ob": jnp.asarray(_t(attn.output.dense.bias)),
        "attn_nw": jnp.asarray(_t(attn.output.LayerNorm.weight)),
        "attn_nb": jnp.asarray(_t(attn.output.LayerNorm.bias)),
        "inter_w": jnp.asarray(_t(bert_layer.intermediate.dense.weight).T),
        "inter_b": jnp.asarray(_t(bert_layer.intermediate.dense.bias)),
        "output_w": jnp.asarray(_t(bert_layer.output.dense.weight).T),
        "output_b": jnp.asarray(_t(bert_layer.output.dense.bias)),
        "norm_w": jnp.asarray(_t(bert_layer.output.LayerNorm.weight)),
        "norm_b": jnp.asarray(_t(bert_layer.output.LayerNorm.bias)),
    }


def _find_bert_layers(model):
    """Locate the list of BertLayer-like submodules in an HF model."""
    for attr_chain in (("bert", "encoder", "layer"),
                       ("encoder", "layer"), ("layer",)):
        obj = model
        ok = True
        for attr in attr_chain:
            if not hasattr(obj, attr):
                ok = False
                break
            obj = getattr(obj, attr)
        if ok:
            return list(obj)
    raise ValueError("could not find a BERT encoder layer list in model")


def replace_transformer_layer(orig_layer_impl, model, micro_batch_size=-1,
                              bert_config=None, seed=-1, max_seq_length=512,
                              preln=False, fp16=True, huggingface=False,
                              local_rank=-1, training=True):
    """Build fused TPU layers from a torch BERT model's weights.

    Returns (layers, params_list, encoder_fn) where
    ``encoder_fn(params_list, hidden_states, attention_mask)`` runs the
    full fused encoder stack (jittable).
    """
    bert_layers = _find_bert_layers(model)
    hidden = bert_config.hidden_size
    cfg = DeepSpeedTransformerConfig(
        batch_size=micro_batch_size,
        hidden_size=hidden,
        intermediate_size=bert_config.intermediate_size,
        heads=bert_config.num_attention_heads,
        attn_dropout_ratio=bert_config.attention_probs_dropout_prob,
        hidden_dropout_ratio=bert_config.hidden_dropout_prob,
        num_hidden_layers=bert_config.num_hidden_layers,
        initializer_range=bert_config.initializer_range,
        layer_norm_eps=getattr(bert_config, "layer_norm_eps", 1e-12),
        seed=seed,
        fp16=fp16,
        pre_layer_norm=preln,
        huggingface=huggingface,
        local_rank=local_rank,
        training=training)

    layers = []
    params_list = []
    for bert_layer in bert_layers:
        layer = DeepSpeedTransformerLayer(cfg)
        layers.append(layer)
        params_list.append(extract_bert_layer_params(bert_layer))

    def encoder_fn(params_list, hidden_states, attention_mask=None,
                   rng=None, deterministic=True):
        x = jnp.asarray(hidden_states)
        for layer, params in zip(layers, params_list):
            x = layer.apply(params, x, attention_mask=attention_mask,
                            rng=rng, deterministic=deterministic)
        return x

    return layers, params_list, encoder_fn
