from .replace_module import (extract_bert_layer_params,
                             replace_transformer_layer)
