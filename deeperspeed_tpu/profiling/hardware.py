"""Per-device-kind hardware peaks (shared by bench.py, the in-engine
telemetry layer `runtime/telemetry.py`, and the schedule planner's
analytic cost model, `deeperspeed_tpu/planner`).

One table per quantity, several consumers: `bench.py` computes offline
MFU from measured tokens/s, the telemetry layer turns
`compiled.cost_analysis()` flops into a live `Train/Samples/mfu`
scalar, and the planner prices candidate schedules (compute from peak
flops, collectives from ICI bandwidth). Keeping the tables here means
the consumers can never disagree about what "peak" means for a chip.

Import-light on purpose: no jax at module scope — callers hand in device
objects (or kind strings), so config parsing never pays a backend init.
"""

# bf16 peak FLOPS by TPU generation (public spec sheet numbers). Matched
# as substrings against the lowercased `device_kind`.
PEAK_FLOPS_BY_KIND = {
    "v5 lite": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v5": 459e12,
    "v4": 275e12,
    "v6": 918e12, "v6e": 918e12,
}

# Conservative default when the kind is unknown (also what CPU test runs
# resolve to — their MFU scalars are meaningless but well-defined).
PEAK_FLOPS_DEFAULT = 197e12


# Per-chip ICI all-gather/reduce-scatter bandwidth in bytes/s (public
# spec sheet aggregate link bandwidth, derated to a sustained-collective
# estimate). Matched like PEAK_FLOPS_BY_KIND. The planner's collective
# model divides bucket bytes by this; it is a ranking signal, not a
# simulator — only relative candidate ordering matters.
ICI_BANDWIDTH_BY_KIND = {
    "v5 lite": 180e9, "v5e": 180e9,
    "v5p": 600e9, "v5": 600e9,
    "v4": 300e9,
    "v6": 360e9, "v6e": 360e9,
}

# CPU/unknown backends: a deliberately low figure so the planner treats
# collectives as expensive and prefers overlap-friendly schedules there.
ICI_BANDWIDTH_DEFAULT = 10e9

# Fixed per-collective launch/latency cost (seconds). Prices the
# many-tiny-buckets failure mode: a 1 MB bucket ladder pays this per
# bucket and loses to fewer, fatter buckets on the analytic ladder.
COLLECTIVE_LATENCY_S = 5e-6


def _by_kind(device, table, default):
    kind = getattr(device, "device_kind", None)
    if kind is None:
        kind = str(device)
    kind = (kind or "").lower()
    for key, val in table.items():
        if key in kind:
            return val
    return default


def peak_flops_per_chip(device):
    """bf16 peak FLOPS for a jax device (or a device-kind string)."""
    return _by_kind(device, PEAK_FLOPS_BY_KIND, PEAK_FLOPS_DEFAULT)


def ici_bandwidth_per_chip(device):
    """Sustained per-chip collective bandwidth (bytes/s) for a jax
    device or a device-kind string."""
    return _by_kind(device, ICI_BANDWIDTH_BY_KIND, ICI_BANDWIDTH_DEFAULT)
