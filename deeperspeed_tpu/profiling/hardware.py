"""Per-device-kind hardware peaks (shared by bench.py and the in-engine
telemetry layer, `runtime/telemetry.py`).

One table, two consumers: `bench.py` computes offline MFU from measured
tokens/s, and the telemetry layer turns `compiled.cost_analysis()` flops
into a live `Train/Samples/mfu` scalar. Keeping the table here means the
two can never disagree about what "peak" means for a chip.

Import-light on purpose: no jax at module scope — callers hand in device
objects (or kind strings), so config parsing never pays a backend init.
"""

# bf16 peak FLOPS by TPU generation (public spec sheet numbers). Matched
# as substrings against the lowercased `device_kind`.
PEAK_FLOPS_BY_KIND = {
    "v5 lite": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v5": 459e12,
    "v4": 275e12,
    "v6": 918e12, "v6e": 918e12,
}

# Conservative default when the kind is unknown (also what CPU test runs
# resolve to — their MFU scalars are meaningless but well-defined).
PEAK_FLOPS_DEFAULT = 197e12


def peak_flops_per_chip(device):
    """bf16 peak FLOPS for a jax device (or a device-kind string)."""
    kind = getattr(device, "device_kind", None)
    if kind is None:
        kind = str(device)
    kind = (kind or "").lower()
    for key, val in PEAK_FLOPS_BY_KIND.items():
        if key in kind:
            return val
    return PEAK_FLOPS_DEFAULT
