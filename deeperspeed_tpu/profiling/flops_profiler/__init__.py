from .profiler import (FlopsProfiler, duration_to_string,
                       flops_to_string, params_to_string, profile_fn)

__all__ = ["FlopsProfiler", "duration_to_string", "flops_to_string",
           "params_to_string", "profile_fn"]
