"""FLOPs profiler (reference:
`deepspeed/profiling/flops_profiler/profiler.py:11`).

The reference counts flops by monkeypatching `torch.nn.functional` and
installing module hooks. On TPU the compiler already knows: XLA's cost
analysis on the *compiled* step reports exact flops/bytes for the whole
fused program, and per-jitted-function breakdown replaces the per-module
tree. Wall-clock comes from fenced timing of the same executable.

`FlopsProfiler(engine)` profiles the engine's compiled train step;
`profile_fn(fn, *args)` profiles any jittable function.
"""

import time

import numpy as np

import jax

from ...utils.logging import logger


def _cost_analysis(compiled):
    try:
        costs = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(costs, (list, tuple)):
        costs = costs[0] if costs else {}
    return dict(costs or {})


def profile_fn(fn, *args, static_argnums=(), n_timing_iters=3, **kwargs):
    """Compile `fn(*args)` and return {flops, bytes_accessed, duration,
    flops_per_sec}; duration measured over `n_timing_iters` fenced runs."""
    jitted = jax.jit(fn, static_argnums=static_argnums)
    lowered = jitted.lower(*args, **kwargs)
    compiled = lowered.compile()
    costs = _cost_analysis(compiled)
    flops = float(costs.get("flops", 0.0))
    bytes_accessed = float(costs.get("bytes accessed", 0.0))

    out = compiled(*args, **kwargs)
    jax.block_until_ready(out)
    start = time.perf_counter()
    for _ in range(n_timing_iters):
        out = compiled(*args, **kwargs)
    jax.block_until_ready(out)
    duration = (time.perf_counter() - start) / n_timing_iters

    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "duration": duration,
        "flops_per_sec": flops / duration if duration > 0 else 0.0,
    }


def params_count(params):
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


class FlopsProfiler:
    """Engine-attached profiler with the reference's method surface."""

    def __init__(self, model=None, engine=None):
        self.engine = engine if engine is not None else model
        self.started = False
        self._results = {}
        self._start_time = None
        self._steps = 0

    # -- lifecycle (reference API) ----------------------------------------

    def start_profile(self, ignore_list=None):
        self.started = True
        self._steps = 0
        self._start_time = time.perf_counter()
        self._results = {}

    def stop_profile(self):
        if not self.started:
            return
        self.started = False
        self._results["duration"] = time.perf_counter() - self._start_time

    def reset_profile(self):
        self._results = {}
        self._steps = 0

    def end_profile(self):
        self.stop_profile()

    def step(self):
        if self.started:
            self._steps += 1

    # -- results -----------------------------------------------------------

    def get_total_flops(self, as_string=False):
        flops = self._results.get("flops", 0.0)
        return flops_to_string(flops) if as_string else flops

    def get_total_duration(self, as_string=False):
        duration = self._results.get("duration", 0.0)
        return duration_to_string(duration) if as_string else duration

    def get_total_params(self, as_string=False):
        n = 0
        if self.engine is not None and hasattr(self.engine, "state"):
            n = params_count(self.engine.state.params)
        return params_to_string(n) if as_string else n

    def profile_train_step(self, batch, accum_steps=None):
        """Cost-analyze the engine's train step on `batch` (stacked
        [accum, global_batch, ...]).

        The step body is profiled through `profile_fn`'s own donation-free
        jit (executing the engine's production step would donate — and so
        invalidate — `engine.state`'s buffers); host-offload engines
        profile their grads-step, which is what their device program
        actually is.
        """
        eng = self.engine
        gas = accum_steps if accum_steps is not None else \
            eng.gradient_accumulation_steps()
        import jax.numpy as jnp
        rng = jax.random.PRNGKey(0)
        sharded = eng._shard_stacked_batch(batch)
        if eng.host_offload:
            results = profile_fn(
                eng._build_grads_step(gas).__wrapped__,
                eng.state.params, sharded, rng, eng.state.scale.cur_scale,
                eng.state.global_steps, n_timing_iters=1)
        else:
            lr = jnp.asarray(eng.optimizer.param_groups[0]["lr"],
                             jnp.float32)
            results = profile_fn(
                eng._build_train_step(gas).__wrapped__,
                eng.state, sharded, rng, lr, n_timing_iters=1)
        self._results.update(results)
        return results

    def print_model_profile(self, profile_step=1, module_depth=-1,
                            top_modules=3, detailed=True, output_file=None):
        lines = [
            "DeepSpeed-TPU Flops Profiler",
            f"params:            {self.get_total_params(as_string=True)}",
            f"flops per step:    {self.get_total_flops(as_string=True)}",
            f"step duration:     {self.get_total_duration(as_string=True)}",
        ]
        if self._results.get("flops_per_sec"):
            lines.append(
                f"achieved:          "
                f"{flops_to_string(self._results['flops_per_sec'])}/s")
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            logger.info(report)
        return report

    def print_model_aggregated_profile(self, module_depth=-1, top_modules=3):
        return self.print_model_profile(module_depth=module_depth,
                                        top_modules=top_modules)


# -- formatting helpers (reference profiler.py bottom section) -------------

def flops_to_string(flops, units=None, precision=2):
    for unit, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if units == unit or (units is None and flops >= scale):
            return f"{round(flops / scale, precision)} {unit}FLOPS"
    return f"{round(flops, precision)} FLOPS"


def params_to_string(params_num, units=None, precision=2):
    for unit, scale in (("B", 1e9), ("M", 1e6), ("k", 1e3)):
        if units == unit or (units is None and params_num >= scale):
            return f"{round(params_num / scale, precision)} {unit}"
    return str(params_num)


def duration_to_string(duration, units=None, precision=2):
    if units == "ms" or (units is None and duration < 1):
        return f"{round(duration * 1000, precision)} ms"
    return f"{round(duration, precision)} s"


def get_model_profile(model, input_res=None, args=None, kwargs=None,
                      print_profile=True, detailed=True, module_depth=-1,
                      top_modules=3, warm_up=1, as_string=True,
                      output_file=None, ignore_modules=None):
    """Standalone helper (reference `profiler.py` tail): profile a jittable
    `model(*args)` callable."""
    args = args or []
    kwargs = kwargs or {}
    results = profile_fn(model, *args, **kwargs)
    flops = results["flops"]
    duration = results["duration"]
    if print_profile:
        logger.info(f"flops={flops_to_string(flops)} "
                    f"duration={duration_to_string(duration)}")
    if as_string:
        return flops_to_string(flops), duration_to_string(duration)
    return flops, duration
