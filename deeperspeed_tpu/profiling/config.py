""""flops_profiler" config block (reference: `deepspeed/profiling/
constants.py`, `config.py`)."""

from dataclasses import dataclass

from ..runtime.config_utils import as_int, get_scalar_param

FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True


@dataclass(frozen=True)
class DeepSpeedFlopsProfilerConfig:
    enabled: bool = FLOPS_PROFILER_ENABLED_DEFAULT
    profile_step: int = FLOPS_PROFILER_PROFILE_STEP_DEFAULT
    module_depth: int = FLOPS_PROFILER_MODULE_DEPTH_DEFAULT
    top_modules: int = FLOPS_PROFILER_TOP_MODULES_DEFAULT
    detailed: bool = FLOPS_PROFILER_DETAILED_DEFAULT

    @classmethod
    def from_dict(cls, param_dict):
        d = param_dict.get(FLOPS_PROFILER) or {}
        return cls(
            enabled=bool(get_scalar_param(
                d, FLOPS_PROFILER_ENABLED, FLOPS_PROFILER_ENABLED_DEFAULT)),
            profile_step=as_int(get_scalar_param(
                d, FLOPS_PROFILER_PROFILE_STEP,
                FLOPS_PROFILER_PROFILE_STEP_DEFAULT),
                FLOPS_PROFILER_PROFILE_STEP),
            module_depth=as_int(get_scalar_param(
                d, FLOPS_PROFILER_MODULE_DEPTH,
                FLOPS_PROFILER_MODULE_DEPTH_DEFAULT),
                FLOPS_PROFILER_MODULE_DEPTH),
            top_modules=as_int(get_scalar_param(
                d, FLOPS_PROFILER_TOP_MODULES,
                FLOPS_PROFILER_TOP_MODULES_DEFAULT),
                FLOPS_PROFILER_TOP_MODULES),
            detailed=bool(get_scalar_param(
                d, FLOPS_PROFILER_DETAILED, FLOPS_PROFILER_DETAILED_DEFAULT)),
        )
