"""Elasticity config keys (reference: `deepspeed/elasticity/constants.py`)."""

ELASTICITY = "elasticity"

ENABLED = "enabled"
ENABLED_DEFAULT = False

MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000

MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]

MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000

MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0

PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False

VERSION = "version"
VERSION_DEFAULT = 0.1
LATEST_ELASTICITY_VERSION = 0.1

MINIMUM_DEEPSPEED_VERSION = "0.3.8"

DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"

# ---------------------------------------------------------------------------
# Resilience sub-blocks (fork addition): peer-health heartbeats and the
# supervised-restart layer. They live INSIDE the "elasticity" JSON block
# next to the batch-solver keys above but are independently gated — a
# job can run heartbeats + supervised restarts without the elastic
# batch arithmetic, and vice versa.
# ---------------------------------------------------------------------------

HEARTBEAT = "heartbeat"
HEARTBEAT_ENABLED = "enabled"
HEARTBEAT_ENABLED_DEFAULT = False
HEARTBEAT_INTERVAL = "interval_s"
HEARTBEAT_INTERVAL_DEFAULT = 5.0
HEARTBEAT_WARN_AFTER = "warn_after_s"
HEARTBEAT_WARN_AFTER_DEFAULT = 15.0
HEARTBEAT_FAIL_AFTER = "fail_after_s"
HEARTBEAT_FAIL_AFTER_DEFAULT = 60.0
HEARTBEAT_EMERGENCY_SAVE = "emergency_checkpoint"
HEARTBEAT_EMERGENCY_SAVE_DEFAULT = True

SUPERVISOR = "supervisor"
SUPERVISOR_ENABLED = "enabled"
SUPERVISOR_ENABLED_DEFAULT = False
SUPERVISOR_MAX_RESTARTS = "max_restarts"
SUPERVISOR_MAX_RESTARTS_DEFAULT = 3
SUPERVISOR_BACKOFF_BASE = "backoff_base_s"
SUPERVISOR_BACKOFF_BASE_DEFAULT = 1.0
SUPERVISOR_BACKOFF_MAX = "backoff_max_s"
SUPERVISOR_BACKOFF_MAX_DEFAULT = 60.0
SUPERVISOR_BACKOFF_JITTER = "backoff_jitter"
SUPERVISOR_BACKOFF_JITTER_DEFAULT = 0.25
SUPERVISOR_POISON_STEP_THRESHOLD = "poison_step_threshold"
SUPERVISOR_POISON_STEP_THRESHOLD_DEFAULT = 3

# Env vars exported by the supervisor into every (re)launched child.
DS_ELASTIC_STATE_DIR = "DS_ELASTIC_STATE_DIR"
DS_ELASTIC_RESTART_COUNT = "DS_ELASTIC_RESTART_COUNT"

# Files inside the elastic state dir.
PROGRESS_FILE = "progress.json"          # child: step heartbeat
SUPERVISOR_FILE = "supervisor.json"      # supervisor: restart record

# Exit code a training process uses for "a PEER died, I am healthy":
# restartable by the supervisor, distinct from local crashes in logs
# and MTTR accounting. 70-79 is free of shell/Python conventions.
EXIT_CODE_PEER_FAILURE = 76

# Exit code for "a SLICE died and this process re-launches with a
# re-partitioned pipeline" (docs/multislice.md). The supervisor treats
# it as recovery, not a crashing step: it never feeds the poison-step
# detector (the step did not fail — the topology did), though it still
# consumes restart budget.
EXIT_CODE_SLICE_REPARTITION = 77
