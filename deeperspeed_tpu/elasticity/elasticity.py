"""Elastic batch/device-count solver (reference:
`deepspeed/elasticity/elasticity.py:122-337`).

Given a set of allowed micro-batch sizes and a ceiling on the global batch,
find the global batch size that divides evenly across the largest number of
device counts, so a job can be rescheduled onto different chip counts without
changing the effective batch (gradient accumulation absorbs the difference).
Pure Python; deterministic for a given config.
"""

import json
import math
import os
import re
from functools import reduce

from ..utils.logging import logger
from . import constants as ec
from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize)

# Smallest 38 highly composite numbers — covers batch sizes up to ~720K.
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720
]


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """For each base, the largest base*HCN not exceeding the ceiling."""
    candidates = set()
    for base in base_list:
        best = base
        for hcn in HCN_LIST:
            scaled = base * hcn
            if scaled > max_acceptable_batch_size:
                break
            best = scaled
        candidates.add(best)
    return list(candidates)


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """All device counts w for which some micro-batch m satisfies
    batch_size == m * k * w for integer k (i.e. w divides batch_size/m)."""
    valid = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_gpus = batch_size // micro_batch
        if min_valid_gpus <= max_gpus <= max_valid_gpus:
            valid.add(max_gpus)
        for i in range(1, max_gpus // 2 + 1):
            if max_gpus % i == 0 and min_valid_gpus <= i <= max_valid_gpus:
                valid.add(i)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus,
                        max_gpus, prefer_larger):
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))

    for batch_size in candidate_batch_sizes:
        current = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        better_tie = (len(current) == max_valid_gpus and
                      ((prefer_larger and batch_size > final_batch_size) or
                       (not prefer_larger and batch_size < final_batch_size)))
        if len(current) > max_valid_gpus or better_tie:
            max_valid_gpus = len(current)
            valid_gpus = current
            final_batch_size = batch_size

    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches,
                             max_acceptable_batch_size,
                             min_gpus=None,
                             max_gpus=None,
                             prefer_larger=True):
    """v0.1 heuristic: candidate batches are each micro-batch (and their LCM)
    scaled to the largest highly-composite multiple under the ceiling; pick
    the candidate compatible with the most device counts."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or int(max_acceptable_batch_size // min(micro_batches))

    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ElasticityConfigError(
            f"All micro batches must be <= max_acceptable_batch_size="
            f"{max_acceptable_batch_size}, got {micro_batches}")

    lcm = reduce(math.lcm, micro_batches)
    base_list = list(micro_batches) + [lcm]
    candidates = get_candidate_batch_sizes(base_list,
                                           max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus,
                               prefer_larger)


def _parse_version(version_str):
    matched = re.search(r"^(\d+)\.(\d+)(?:\.(\d+))?", version_str)
    if matched is None:
        raise ElasticityError(
            f"Cannot parse version {version_str!r}; expected major.minor[.patch]")
    return (int(matched.group(1)), int(matched.group(2)),
            int(matched.group(3) or 0))


def _compatible_ds_version_check(target_version):
    minimum = _parse_version(ec.MINIMUM_DEEPSPEED_VERSION)
    target = _parse_version(target_version)
    if target < minimum:
        raise ElasticityError(
            f"Target version {target_version} is below the minimum "
            f"{ec.MINIMUM_DEEPSPEED_VERSION} supporting elasticity.")
    return True


def elasticity_enabled(ds_config):
    if ec.ELASTICITY not in ds_config:
        return False
    return ds_config[ec.ELASTICITY].get(ec.ENABLED, ec.ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Verify the scheduler-stamped elastic config (env fingerprint) matches
    the runtime one, so a rescheduled job cannot silently drift."""
    if ec.DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            f"{ec.DEEPSPEED_ELASTICITY_CONFIG} env var not found; cannot "
            "guarantee the resource scheduler will scale this job with "
            "compatible device counts.")
        return
    scheduler = ElasticityConfig(
        json.loads(os.environ[ec.DEEPSPEED_ELASTICITY_CONFIG]))
    runtime = ElasticityConfig(runtime_elastic_config_dict)
    for attr in ("max_acceptable_batch_size", "micro_batches", "version"):
        if getattr(runtime, attr) != getattr(scheduler, attr):
            raise ElasticityConfigError(
                f"Elastic config '{attr}={getattr(scheduler, attr)}' seen by "
                f"the resource scheduler does not match runtime "
                f"{attr}={getattr(runtime, attr)}")


def compute_elastic_config(ds_config, target_deepspeed_version, world_size=0):
    """Compute (final_batch_size, valid_gpus[, micro_batch]) for an elastic
    job; deterministic for a given ds_config. See reference
    `elasticity.py:240` for the contract."""
    if not isinstance(ds_config, dict):
        raise ValueError(
            f"Expected ds_config dict, got {type(ds_config).__name__}")

    if ec.ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            f"'{ec.ELASTICITY}' is missing from the config json; add it if "
            "running an elastic training job.")

    elastic_config_dict = ds_config[ec.ELASTICITY]
    if not elastic_config_dict.get(ec.ENABLED, ec.ENABLED_DEFAULT):
        raise ElasticityConfigError(
            "Elasticity is disabled; set 'enabled': true to run elastic.")

    elastic_config = ElasticityConfig(elastic_config_dict)

    if float(elastic_config.version) > ec.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Elasticity version {elastic_config.version} newer than latest "
            f"supported {ec.LATEST_ELASTICITY_VERSION}")

    _compatible_ds_version_check(target_deepspeed_version)

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(
            f"No elasticity logic for version {elastic_config.version}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not in the valid device-count "
                f"list: {valid_gpus}")
        micro_batch_size = None
        for mbsz in sorted(set(elastic_config.micro_batches), reverse=True):
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        if micro_batch_size is None:
            raise ElasticityError(
                f"No micro batch divides final_batch_size={final_batch_size} "
                f"at world_size={world_size}")
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus
