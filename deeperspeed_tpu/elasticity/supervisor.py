"""Supervised restart with capped exponential backoff.

The missing half of the resilience loop: peer-health detection
(`heartbeat.py`) and emergency checkpoints (PR 3) get a wounded job OFF
the accelerators cleanly, but nothing brought it BACK — the launcher
simply exited with the child's return code. `Supervisor` closes the
loop: it relaunches the training process on restartable failures with

- **capped exponential backoff + jitter** — ``backoff_base_s * 2**k``
  up to ``backoff_max_s``, each scaled by a uniform jitter so a fleet
  of per-host supervisors does not stampede the coordinator;
- **a restart budget** — ``max_restarts`` relaunches total, then a
  typed `RestartBudgetExceededError`;
- **a poison-step detector** — the child reports its training step via
  a progress file (written by the engine at every step boundary when
  ``DS_ELASTIC_STATE_DIR`` is exported, which the supervisor does); the
  SAME step crashing ``poison_step_threshold`` times in a row means the
  failure is deterministic and restarting would loop forever — a typed
  `PoisonStepError` aborts instead.

Restartability: exit code 0 is success; `EXIT_CODE_PEER_FAILURE` (a
healthy process exiting because a PEER died) and any other nonzero code
(crash, OOM-kill, preemption SIGKILL) are restartable — the budget and
the poison detector bound the loop, so an honest crash-restart cycle
is safe to attempt.

MTTR accounting: before each relaunch the supervisor writes
``supervisor.json`` (crash wall-time, exit code, restart count) into
the state dir; the restarted engine reads it at init and emits
``Train/Elastic/mttr_s`` / ``restart_count`` scalars, so recovery
latency is measured end to end by the system itself.
"""

import json
import os
import random
import subprocess
import sys
import time

from ..utils.logging import logger
from . import constants as ec
from .config import PoisonStepError, RestartBudgetExceededError

_SLEEP_CHUNK_S = 0.2   # stop_requested is honored mid-backoff


def read_progress(state_dir):
    """The child's last progress record ({"global_steps": N, ...}), or
    None when it never got far enough to write one."""
    path = os.path.join(state_dir, ec.PROGRESS_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_progress(state_dir, global_steps, committed_step=None):
    """Atomic progress write (engine step boundary): the supervisor must
    never read a torn record mid-crash."""
    # true epoch timestamp: the record is read by ANOTHER process (the
    # supervisor) — a per-process monotonic clock is meaningless there
    record = {"global_steps": int(global_steps),
              "time": time.time()}  # dslint: disable=wall-clock
    if committed_step is not None:
        record["committed_step"] = int(committed_step)
    tmp = os.path.join(state_dir, ec.PROGRESS_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, os.path.join(state_dir, ec.PROGRESS_FILE))


def read_restart_record(state_dir=None):
    """The supervisor's pre-relaunch record for THIS incarnation (crash
    time / exit code / restart count), or None on a first launch. The
    engine calls this (state dir from `DS_ELASTIC_STATE_DIR`) to emit
    the MTTR + restart-count telemetry scalars."""
    state_dir = state_dir or os.environ.get(ec.DS_ELASTIC_STATE_DIR)
    if not state_dir:
        return None
    try:
        with open(os.path.join(state_dir, ec.SUPERVISOR_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class Supervisor:
    """Run a training child under restart supervision.

    ``argv`` is the child command line (the launcher passes the user
    script + args); ``state_dir`` holds the progress/supervisor files
    and is exported to the child as ``DS_ELASTIC_STATE_DIR`` along with
    ``DS_ELASTIC_RESTART_COUNT``. ``popen_fn``/``sleep_fn``/``rng`` are
    injection seams for deterministic tests."""

    def __init__(self, argv, state_dir, env=None, max_restarts=3,
                 backoff_base_s=1.0, backoff_max_s=60.0,
                 backoff_jitter=0.25, poison_step_threshold=3,
                 popen_fn=None, sleep_fn=None, rng=None):
        self.argv = list(argv)
        self.state_dir = state_dir
        self.env = dict(os.environ if env is None else env)
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self.poison_step_threshold = int(poison_step_threshold)
        self._popen = popen_fn or (
            lambda argv, env: subprocess.Popen(argv, env=env))
        self._sleep = sleep_fn or time.sleep
        self._rng = rng or random.Random()
        self.stop_requested = False
        self._child = None

        self.restarts = 0
        self.exit_codes = []
        self.crash_steps = []
        self._same_step_crashes = 0
        self._last_crash_step = None
        self.total_backoff_s = 0.0

    # -- policy ------------------------------------------------------------

    def backoff_s(self, attempt):
        """Backoff before restart `attempt` (1-based): the shared
        capped-exponential × uniform-jitter law
        (`utils.kv_retry.backoff_delay`)."""
        from ..utils.kv_retry import backoff_delay
        return backoff_delay(attempt, self.backoff_base_s,
                             self.backoff_max_s, self.backoff_jitter,
                             self._rng)

    def _record_crash_step(self, crashing=True):
        """Book the exiting child's last step. ``crashing`` False (a
        slice re-partition, `EXIT_CODE_SLICE_REPARTITION`) records the
        step for the stats/restart record but does NOT feed the
        poison-step detector: the step did not fail — the topology did
        — and the re-partitioned child will legitimately replay it
        (re-partition is recovery, not a crashing step)."""
        progress = read_progress(self.state_dir)
        step = None if progress is None else progress.get("global_steps")
        self.crash_steps.append(step)
        if not crashing:
            return step
        if step is not None and step == self._last_crash_step:
            self._same_step_crashes += 1
        else:
            self._same_step_crashes = 1
        self._last_crash_step = step
        return step

    # -- the supervision loop ---------------------------------------------

    def _spawn(self):
        env = dict(self.env)
        env[ec.DS_ELASTIC_STATE_DIR] = self.state_dir
        env[ec.DS_ELASTIC_RESTART_COUNT] = str(self.restarts)
        self._child = self._popen(self.argv, env)
        return self._child

    def terminate_child(self):
        """Forward a shutdown (launcher SIGTERM/SIGINT) to the child and
        stop restarting."""
        self.stop_requested = True
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.terminate()
            except OSError:  # pragma: no cover
                pass

    def run(self):
        """Supervise until the child exits 0 (returns stats), the budget
        runs out (`RestartBudgetExceededError`), the same step keeps
        crashing (`PoisonStepError`), or a stop is requested (returns
        stats with the child's last exit code)."""
        os.makedirs(self.state_dir, exist_ok=True)
        # stale records from a PREVIOUS supervision session in a reused
        # state dir would poison this one: an old progress.json mis-
        # attributes startup crashes to its step (false poison-step
        # aborts), an old supervisor.json feeds the restarted engine a
        # bogus days-long MTTR. Records written DURING this session
        # survive restarts — only the pre-session leftovers go.
        for stale in (ec.PROGRESS_FILE, ec.SUPERVISOR_FILE):
            try:
                os.remove(os.path.join(self.state_dir, stale))
            except OSError:
                pass
        while True:
            child = self._spawn()
            rc = child.wait()
            self._child = None
            if rc == 0:
                return self.stats(exit_code=0)
            self.exit_codes.append(rc)
            if self.stop_requested:
                logger.info(f"supervisor: stop requested; child exited "
                            f"{rc}, not restarting")
                return self.stats(exit_code=rc)

            repartition = rc == ec.EXIT_CODE_SLICE_REPARTITION
            crash_step = self._record_crash_step(crashing=not repartition)
            if repartition:
                kind = "slice re-partition"
            elif rc == ec.EXIT_CODE_PEER_FAILURE:
                kind = "peer failure"
            else:
                kind = "crash"
            if self._same_step_crashes >= self.poison_step_threshold:
                raise PoisonStepError(
                    f"step {crash_step} crashed "
                    f"{self._same_step_crashes} times in a row "
                    f"(poison_step_threshold="
                    f"{self.poison_step_threshold}); the failure is "
                    f"deterministic — aborting instead of looping. "
                    f"Exit codes: {self.exit_codes}")
            if self.restarts >= self.max_restarts:
                raise RestartBudgetExceededError(
                    f"child exited {rc} ({kind}) and the restart budget "
                    f"({self.max_restarts}) is exhausted; aborting. "
                    f"Exit codes: {self.exit_codes}, crash steps: "
                    f"{self.crash_steps}")

            self.restarts += 1
            backoff = self.backoff_s(self.restarts)
            self.total_backoff_s += backoff
            logger.warning(
                f"supervisor: child exited {rc} ({kind}) at step "
                f"{crash_step}; restart {self.restarts}/"
                f"{self.max_restarts} in {backoff:.1f}s")
            self._write_restart_record(rc, crash_step, backoff)
            self._interruptible_sleep(backoff)
            if self.stop_requested:
                return self.stats(exit_code=rc)

    def _interruptible_sleep(self, seconds):
        deadline = time.monotonic() + seconds
        while not self.stop_requested:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._sleep(min(remaining, _SLEEP_CHUNK_S))

    def _write_restart_record(self, exit_code, crash_step, backoff):
        record = {
            # true epoch timestamp: MTTR is measured by the RESTARTED
            # process (engine.py) against this value — monotonic clocks
            # don't survive the process boundary
            "crash_time": time.time(),  # dslint: disable=wall-clock
            "exit_code": int(exit_code),
            "crash_step": crash_step,
            "restart_count": self.restarts,
            "backoff_s": backoff,
        }
        tmp = os.path.join(self.state_dir, ec.SUPERVISOR_FILE + ".tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp,
                       os.path.join(self.state_dir, ec.SUPERVISOR_FILE))
        except OSError as e:  # pragma: no cover - state dir vanished
            logger.warning(f"supervisor: could not write restart "
                           f"record: {e}")

    def stats(self, exit_code=0):
        return {
            "exit_code": exit_code,
            "restarts": self.restarts,
            "exit_codes": list(self.exit_codes),
            "crash_steps": list(self.crash_steps),
            "total_backoff_s": self.total_backoff_s,
        }


def supervised_exit_code(exc):
    """Map a training-loop exception to the conventional process exit
    code (`PeerFailureError` carries its own; everything else is 1)."""
    return getattr(exc, "exit_code", 1)


def main(argv=None):  # pragma: no cover - thin CLI shim
    """``python -m deeperspeed_tpu.elasticity.supervisor [opts] --
    <child argv>`` — the standalone form of what `launcher/launch.py
    --elastic` does inline."""
    import argparse
    parser = argparse.ArgumentParser(description="DeeperSpeed-TPU "
                                     "elastic restart supervisor")
    parser.add_argument("--state_dir", required=True)
    parser.add_argument("--max_restarts", type=int,
                        default=ec.SUPERVISOR_MAX_RESTARTS_DEFAULT)
    parser.add_argument("--backoff_base_s", type=float,
                        default=ec.SUPERVISOR_BACKOFF_BASE_DEFAULT)
    parser.add_argument("--backoff_max_s", type=float,
                        default=ec.SUPERVISOR_BACKOFF_MAX_DEFAULT)
    parser.add_argument("--backoff_jitter", type=float,
                        default=ec.SUPERVISOR_BACKOFF_JITTER_DEFAULT)
    parser.add_argument("--poison_step_threshold", type=int,
                        default=ec.SUPERVISOR_POISON_STEP_THRESHOLD_DEFAULT)
    parser.add_argument("child", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    child = [a for a in args.child if a != "--"]
    if not child:
        parser.error("no child command given")
    supervisor = Supervisor(
        child, args.state_dir, max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base_s,
        backoff_max_s=args.backoff_max_s,
        backoff_jitter=args.backoff_jitter,
        poison_step_threshold=args.poison_step_threshold)
    stats = supervisor.run()
    sys.exit(stats["exit_code"])


if __name__ == "__main__":  # pragma: no cover
    main()
