"""Elasticity config object (reference: `deepspeed/elasticity/config.py:29`)."""

from . import constants as ec


class ElasticityError(Exception):
    """Base exception for elasticity errors."""


class ElasticityConfigError(ElasticityError):
    """Malformed elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size is not in the valid device-count list for this config."""


class PeerFailureError(ElasticityError, SystemExit):
    """A PEER host died or went silent (heartbeat staleness past
    `fail_after_s`, or a commit barrier timed out on a missing host)
    while THIS process is healthy.

    Subclasses SystemExit so an UNCAUGHT raise ends the process with
    `constants.EXIT_CODE_PEER_FAILURE` (also `.exit_code`/`.code` here)
    rather than a generic traceback-and-1 — that exit code is how the
    supervisor tells restartable peer loss from a local crash, and it
    must hold without every training script adding a handler. It still
    derives from `ElasticityError`, so `except Exception` /
    `except ElasticityError` handlers see it as usual."""

    def __init__(self, message, peers=None, staleness_s=None, cause=None):
        self.peers = list(peers or [])
        self.staleness_s = staleness_s
        self.cause = cause
        self.exit_code = ec.EXIT_CODE_PEER_FAILURE
        super().__init__(message)
        # SystemExit's interpreter-exit hook reads `.code`; our __init__
        # chain set args=(message,), so pin the numeric code explicitly
        self.code = self.exit_code


class RestartBudgetExceededError(ElasticityError):
    """The supervisor exhausted its restart budget: the job keeps dying
    faster than the budget allows — stop burning the queue and page a
    human."""


class PoisonStepError(ElasticityError):
    """The SAME training step crashed `poison_step_threshold` times in a
    row: the failure is deterministic (bad batch, corrupt checkpoint,
    code bug), so restarting would loop forever. Abort instead."""


class SliceLostError(ElasticityError):
    """A whole SLICE died (every failed heartbeat peer maps to a dead
    slice) while this slice is healthy and `multislice.
    survive_slice_loss` is armed.

    Deliberately NOT a `SystemExit`: slice loss is recoverable
    IN-PROCESS — the surviving slices re-partition the pipeline through
    the natural-layout checkpoint stage-change path
    (`elasticity.slices.repartition_after_slice_loss`) and resume
    without a job-wide kill. Callers that do choose a supervised
    re-launch should exit with `.exit_code`
    (`constants.EXIT_CODE_SLICE_REPARTITION`), which the supervisor
    books as recovery rather than a crashing step.

    `detected_at` is the `time.monotonic()` stamp at escalation; the
    recovered engine emits `Train/Elastic/slice_mttr_s` relative to it
    at its first step boundary."""

    def __init__(self, message, lost_slices=None, detected_at=None,
                 peers=None, staleness_s=None):
        self.lost_slices = list(lost_slices or [])
        self.detected_at = detected_at
        self.peers = list(peers or [])
        self.staleness_s = staleness_s
        self.exit_code = ec.EXIT_CODE_SLICE_REPARTITION
        super().__init__(message)


class TopologyChangeError(ElasticityError):
    """A checkpoint was saved under a topology this engine cannot
    elastically absorb (model-parallel/model-axis world changed): the
    sharded layouts differ structurally, and re-slicing silently would
    corrupt the weights. Re-shard offline or restore the old mesh."""


class ElasticityConfig:
    """Parsed "elasticity" block.

    Required when enabled: ``max_train_batch_size`` and ``micro_batch_sizes``.
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ec.ENABLED, ec.ENABLED_DEFAULT)
        if self.enabled:
            for required in (ec.MAX_ACCEPTABLE_BATCH_SIZE, ec.MICRO_BATCHES):
                if required not in param_dict:
                    raise ElasticityConfigError(
                        f"Elasticity config missing {required}")
            self.max_acceptable_batch_size = param_dict[
                ec.MAX_ACCEPTABLE_BATCH_SIZE]
            self.micro_batches = param_dict[ec.MICRO_BATCHES]
        else:
            self.max_acceptable_batch_size = param_dict.get(
                ec.MAX_ACCEPTABLE_BATCH_SIZE,
                ec.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(ec.MICRO_BATCHES,
                                                ec.MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must be a list, got "
                f"{type(self.micro_batches).__name__}: {self.micro_batches}")
        if not all(isinstance(m, int) and not isinstance(m, bool)
                   for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must contain only integers, got "
                f"{self.micro_batches}")
        if not all(m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must contain only positive integers, got "
                f"{self.micro_batches}")

        self.min_gpus = param_dict.get(ec.MIN_GPUS, ec.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(ec.MAX_GPUS, ec.MAX_GPUS_DEFAULT)
        self.min_time = param_dict.get(ec.MIN_TIME, ec.MIN_TIME_DEFAULT)
        self.version = param_dict.get(ec.VERSION, ec.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            ec.PREFER_LARGER_BATCH, ec.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            ec.IGNORE_NON_ELASTIC_BATCH_INFO,
            ec.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return f"ElasticityConfig({self.__dict__})"


# ---------------------------------------------------------------------------
# Resilience sub-blocks: "elasticity": {"heartbeat": {...},
# "supervisor": {...}} — validated at the checkpoint-block parse
# strictness the repo standardizes on (unknown keys / bad types / bad
# ranges raise at startup, not at the first failure hours later).
# ---------------------------------------------------------------------------

def _require_number(block, where, key, default, lo=None, lo_open=False):
    value = block.get(key, default)
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        raise ElasticityConfigError(
            f"{where}.{key} must be a number, got {value!r}")
    value = float(value)
    if lo is not None and (value <= lo if lo_open else value < lo):
        op = ">" if lo_open else ">="
        raise ElasticityConfigError(
            f"{where}.{key} must be {op} {lo}, got {value}")
    return value


def _require_int(block, where, key, default, lo=0):
    value = block.get(key, default)
    if not isinstance(value, int) or isinstance(value, bool):
        raise ElasticityConfigError(
            f"{where}.{key} must be an int, got {value!r}")
    if value < lo:
        raise ElasticityConfigError(
            f"{where}.{key} must be >= {lo}, got {value}")
    return value


def _require_bool(block, where, key, default):
    value = block.get(key, default)
    if not isinstance(value, bool):
        raise ElasticityConfigError(
            f"{where}.{key} must be a boolean, got {value!r}")
    return value


def parse_heartbeat_block(block):
    """Validate "elasticity.heartbeat" -> params dict, or False when
    absent/disabled."""
    block = block or {}
    where = f"{ec.ELASTICITY}.{ec.HEARTBEAT}"
    known = {ec.HEARTBEAT_ENABLED, ec.HEARTBEAT_INTERVAL,
             ec.HEARTBEAT_WARN_AFTER, ec.HEARTBEAT_FAIL_AFTER,
             ec.HEARTBEAT_EMERGENCY_SAVE}
    unknown = sorted(set(block) - known)
    if unknown:
        raise ElasticityConfigError(
            f"Unknown {where} key(s) {unknown}; valid keys: "
            f"{sorted(known)}")
    if not _require_bool(block, where, ec.HEARTBEAT_ENABLED,
                         ec.HEARTBEAT_ENABLED_DEFAULT):
        return False
    interval = _require_number(block, where, ec.HEARTBEAT_INTERVAL,
                               ec.HEARTBEAT_INTERVAL_DEFAULT,
                               lo=0.0, lo_open=True)
    warn_after = _require_number(block, where, ec.HEARTBEAT_WARN_AFTER,
                                 ec.HEARTBEAT_WARN_AFTER_DEFAULT,
                                 lo=0.0, lo_open=True)
    fail_after = _require_number(block, where, ec.HEARTBEAT_FAIL_AFTER,
                                 ec.HEARTBEAT_FAIL_AFTER_DEFAULT,
                                 lo=0.0, lo_open=True)
    if not interval < warn_after <= fail_after:
        raise ElasticityConfigError(
            f"{where} thresholds must satisfy "
            f"{ec.HEARTBEAT_INTERVAL} < {ec.HEARTBEAT_WARN_AFTER} <= "
            f"{ec.HEARTBEAT_FAIL_AFTER}, got {interval} / {warn_after} "
            f"/ {fail_after} (a warn threshold at or below the publish "
            "interval flags every healthy peer)")
    return {
        "interval_s": interval,
        "warn_after_s": warn_after,
        "fail_after_s": fail_after,
        "emergency_checkpoint": _require_bool(
            block, where, ec.HEARTBEAT_EMERGENCY_SAVE,
            ec.HEARTBEAT_EMERGENCY_SAVE_DEFAULT),
    }


def parse_supervisor_block(block):
    """Validate "elasticity.supervisor" -> params dict, or False when
    absent/disabled."""
    block = block or {}
    where = f"{ec.ELASTICITY}.{ec.SUPERVISOR}"
    known = {ec.SUPERVISOR_ENABLED, ec.SUPERVISOR_MAX_RESTARTS,
             ec.SUPERVISOR_BACKOFF_BASE, ec.SUPERVISOR_BACKOFF_MAX,
             ec.SUPERVISOR_BACKOFF_JITTER,
             ec.SUPERVISOR_POISON_STEP_THRESHOLD}
    unknown = sorted(set(block) - known)
    if unknown:
        raise ElasticityConfigError(
            f"Unknown {where} key(s) {unknown}; valid keys: "
            f"{sorted(known)}")
    if not _require_bool(block, where, ec.SUPERVISOR_ENABLED,
                         ec.SUPERVISOR_ENABLED_DEFAULT):
        return False
    base = _require_number(block, where, ec.SUPERVISOR_BACKOFF_BASE,
                           ec.SUPERVISOR_BACKOFF_BASE_DEFAULT,
                           lo=0.0, lo_open=True)
    cap = _require_number(block, where, ec.SUPERVISOR_BACKOFF_MAX,
                          ec.SUPERVISOR_BACKOFF_MAX_DEFAULT,
                          lo=0.0, lo_open=True)
    if cap < base:
        raise ElasticityConfigError(
            f"{where}.{ec.SUPERVISOR_BACKOFF_MAX} ({cap}) must be >= "
            f"{ec.SUPERVISOR_BACKOFF_BASE} ({base})")
    jitter = _require_number(block, where, ec.SUPERVISOR_BACKOFF_JITTER,
                             ec.SUPERVISOR_BACKOFF_JITTER_DEFAULT, lo=0.0)
    if jitter > 1.0:
        raise ElasticityConfigError(
            f"{where}.{ec.SUPERVISOR_BACKOFF_JITTER} must be in [0, 1] "
            f"(a fraction of the backoff), got {jitter}")
    return {
        "max_restarts": _require_int(
            block, where, ec.SUPERVISOR_MAX_RESTARTS,
            ec.SUPERVISOR_MAX_RESTARTS_DEFAULT, lo=0),
        "backoff_base_s": base,
        "backoff_max_s": cap,
        "backoff_jitter": jitter,
        "poison_step_threshold": _require_int(
            block, where, ec.SUPERVISOR_POISON_STEP_THRESHOLD,
            ec.SUPERVISOR_POISON_STEP_THRESHOLD_DEFAULT, lo=2),
    }


def parse_resilience_config(param_dict):
    """Parse the resilience sub-blocks out of a full ds_config dict:
    ``{"heartbeat": {...}|False, "supervisor": {...}|False}``. Unknown
    TOP-LEVEL elasticity keys also reject here (the batch-solver keys
    plus the two sub-blocks are the whole schema)."""
    block = param_dict.get(ec.ELASTICITY) or {}
    if not isinstance(block, dict):
        raise ElasticityConfigError(
            f"'{ec.ELASTICITY}' must be an object, got "
            f"{type(block).__name__}")
    known = {ec.ENABLED, ec.MAX_ACCEPTABLE_BATCH_SIZE, ec.MICRO_BATCHES,
             ec.MIN_GPUS, ec.MAX_GPUS, ec.MIN_TIME, ec.VERSION,
             ec.PREFER_LARGER_BATCH, ec.IGNORE_NON_ELASTIC_BATCH_INFO,
             ec.HEARTBEAT, ec.SUPERVISOR}
    unknown = sorted(set(block) - known)
    if unknown:
        raise ElasticityConfigError(
            f"Unknown '{ec.ELASTICITY}' key(s) {unknown}; valid keys: "
            f"{sorted(known)}")
    return {
        "heartbeat": parse_heartbeat_block(block.get(ec.HEARTBEAT)),
        "supervisor": parse_supervisor_block(block.get(ec.SUPERVISOR)),
    }
