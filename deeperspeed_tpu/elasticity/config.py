"""Elasticity config object (reference: `deepspeed/elasticity/config.py:29`)."""

from . import constants as ec


class ElasticityError(Exception):
    """Base exception for elasticity errors."""


class ElasticityConfigError(ElasticityError):
    """Malformed elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size is not in the valid device-count list for this config."""


class ElasticityConfig:
    """Parsed "elasticity" block.

    Required when enabled: ``max_train_batch_size`` and ``micro_batch_sizes``.
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ec.ENABLED, ec.ENABLED_DEFAULT)
        if self.enabled:
            for required in (ec.MAX_ACCEPTABLE_BATCH_SIZE, ec.MICRO_BATCHES):
                if required not in param_dict:
                    raise ElasticityConfigError(
                        f"Elasticity config missing {required}")
            self.max_acceptable_batch_size = param_dict[
                ec.MAX_ACCEPTABLE_BATCH_SIZE]
            self.micro_batches = param_dict[ec.MICRO_BATCHES]
        else:
            self.max_acceptable_batch_size = param_dict.get(
                ec.MAX_ACCEPTABLE_BATCH_SIZE,
                ec.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(ec.MICRO_BATCHES,
                                                ec.MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must be a list, got "
                f"{type(self.micro_batches).__name__}: {self.micro_batches}")
        if not all(isinstance(m, int) and not isinstance(m, bool)
                   for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must contain only integers, got "
                f"{self.micro_batches}")
        if not all(m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must contain only positive integers, got "
                f"{self.micro_batches}")

        self.min_gpus = param_dict.get(ec.MIN_GPUS, ec.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(ec.MAX_GPUS, ec.MAX_GPUS_DEFAULT)
        self.min_time = param_dict.get(ec.MIN_TIME, ec.MIN_TIME_DEFAULT)
        self.version = param_dict.get(ec.VERSION, ec.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            ec.PREFER_LARGER_BATCH, ec.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            ec.IGNORE_NON_ELASTIC_BATCH_INFO,
            ec.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return f"ElasticityConfig({self.__dict__})"
