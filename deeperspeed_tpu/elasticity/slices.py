"""Slice-loss recovery: re-partition the surviving slices IN-PROCESS
(docs/multislice.md walkthrough).

When the peer-health monitor escalates a dead slice as
`SliceLostError` (multislice.survive_slice_loss), the job is NOT lost:
the surviving slices hold a complete copy of the optimizer state in the
last checkpoint's NATURAL layout, and the stage-change resume path
(`checkpoint/checkpointing.py`) re-partitions a stacked pipeline layout
across any stage count >= 2. This module drives that path end to end —
build the surviving config, initialize a fresh engine over the
surviving mesh, load the checkpoint through the re-partition path, and
stamp the recovery record the engine turns into
`Train/Elastic/slice_mttr_s` at its first step boundary.

No process exits, no supervisor round-trip: MTTR is bounded by
checkpoint load + recompile, not by scheduler re-queue. A caller that
WANTS the supervised re-launch instead (e.g. the lost slice held this
very host) exits with `err.exit_code`
(`constants.EXIT_CODE_SLICE_REPARTITION`), which the supervisor books
as recovery — it never feeds the poison-step detector.
"""

import logging
import time

from .config import SliceLostError

logger = logging.getLogger(__name__)


def repartition_after_slice_loss(err, raw_config, model_factory,
                                 load_dir, topology=None, tag=None,
                                 **initialize_kwargs):
    """Recover from `err` (a `SliceLostError`) without restarting.

    Args:
      err: the escalated SliceLostError (carries `lost_slices` and the
        `detected_at` monotonic stamp MTTR is measured from).
      raw_config: the raw config DICT the lost run was initialized with
        (the surviving config derives from it —
        `parallel.multislice.surviving_raw_config`).
      model_factory: callable(surviving_raw_config) -> fresh model
        object; the engine applies the surviving config to it
        (`apply_ds_config`), so the lost engine's model must not be
        reused.
      load_dir: checkpoint directory to re-partition from (typically
        the lost engine's emergency save target).
      topology: the lost engine's `SliceTopology`; defaults to
        rebuilding it from `raw_config`.
      tag: checkpoint tag (None = latest committed).
      initialize_kwargs: forwarded to `deeperspeed_tpu.initialize`
        (mesh=, optimizer=, ...).

    Returns (engine, surviving_raw_config).
    """
    if not isinstance(err, SliceLostError):
        raise TypeError(f"expected SliceLostError, got {type(err).__name__}")
    from ..runtime.config import DeepSpeedConfig
    from ..parallel.multislice import SliceTopology, surviving_raw_config
    if topology is None:
        parsed = DeepSpeedConfig(raw_config)
        if parsed.multislice_config is None:
            raise ValueError(
                "raw_config has no multislice block — nothing to "
                "re-partition")
        topology = SliceTopology.from_config(parsed.multislice_config,
                                             parsed.pipeline_config)
    surv_cfg = surviving_raw_config(raw_config, topology,
                                    err.lost_slices)
    t_load = time.monotonic()
    model = model_factory(surv_cfg)
    import deeperspeed_tpu as ds
    engine = ds.initialize(model=model, config=surv_cfg,
                           **initialize_kwargs)[0]
    engine.load_checkpoint(load_dir, tag=tag)
    # the recovered engine emits Train/Elastic/slice_mttr_s (and the
    # lost-slice count) at its FIRST step boundary, measured from the
    # monitor's detection stamp — the bounded-MTTR contract
    engine._slice_recovery_record = {
        "detected_at": (err.detected_at if err.detected_at is not None
                        else t_load),
        "lost_slices": list(err.lost_slices),
    }
    logger.warning(
        "slice re-partition complete: lost %s, resumed from %s in "
        "%.2fs (recompile amortizes over the next steps)",
        err.lost_slices, load_dir, time.monotonic() - t_load)
    return engine, surv_cfg
