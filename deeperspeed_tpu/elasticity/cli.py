"""``ds_elastic`` CLI: inspect an elastic config and print the compatible
(total batch, chip-count) combinations (capability of reference
`bin/ds_elastic`, which drives `elasticity/elasticity.py:240`).
"""

import argparse
import json

from ..version import __version__
from .elasticity import compute_elastic_config


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="DeeperSpeed-TPU elastic-training configuration helper")
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed config json with an 'elasticity' "
                             "block")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="intended world size (chips); when given, also "
                             "prints the resolved micro-batch per chip")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        ds_config = json.load(f)

    if args.world_size > 0:
        batch, valid_chips, micro_per_chip = compute_elastic_config(
            ds_config, __version__, world_size=args.world_size)
        print(f"world_size={args.world_size}: train_batch_size={batch}, "
              f"micro_batch_per_chip={micro_per_chip}")
    else:
        batch, valid_chips = compute_elastic_config(ds_config, __version__)
        print(f"valid chip counts: {valid_chips}")
        print(f"chosen max train_batch_size: {batch}")


if __name__ == "__main__":
    main()
