from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize)
from .elasticity import (compute_elastic_config, elasticity_enabled,
                         ensure_immutable_elastic_config)
