from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize, PeerFailureError,
                     PoisonStepError, RestartBudgetExceededError,
                     SliceLostError, TopologyChangeError,
                     parse_heartbeat_block, parse_resilience_config,
                     parse_supervisor_block)
from .elasticity import (compute_elastic_config, elasticity_enabled,
                         ensure_immutable_elastic_config)
from .heartbeat import (InMemoryTransport, PeerHealthMonitor,
                        build_peer_monitor, suspect_peers)
from .slices import repartition_after_slice_loss
from .supervisor import Supervisor, supervised_exit_code
