"""Peer-health detection over coordination-service heartbeats.

PR 4's hang watchdog answers "is THIS process stuck?"; on a multi-host
pod the dominant failure mode is the opposite one — a PEER host dying
or being preempted mid-run, which previously surfaced only as a
DEADLINE_EXCEEDED out of `utils.distributed.barrier()` (or, worse, an
unbounded hang inside a device collective) with no way to tell WHICH
host vanished. This module closes that gap:

- every process publishes a monotonic heartbeat (serial + training
  step) to a shared key-value store — the same `jax.distributed`
  coordination client the barrier helper already uses, so no extra
  service is deployed;
- a daemon thread consumes every peer's stream and tracks staleness by
  LOCAL observation time (when did *I* last see this peer's serial
  advance) — no cross-host clock comparison;
- staleness escalates per peer: ``ok`` → ``slow`` (past ``warn_after_s``;
  logged once, telemetry scalar) → ``dead`` (past ``fail_after_s``).
  A dead peer sets a flag the engine reads at the next step boundary
  (the preemption-handler pattern: detection on the thread, action on
  the main thread) — emergency checkpoint, then a typed
  `PeerFailureError` whose exit code the supervisor recognizes as
  restartable.

The transport is pluggable: `CoordinationTransport` (multi-host,
coordination-service KV) and `InMemoryTransport` (single-process). The
fault-injection harness (`runtime/fault_injection.py` ``peer_death`` /
``slow_peer``) drives SIMULATED peers through the in-memory transport,
so the full detect → checkpoint → exit → supervised-restart loop is
testable on one host.
"""

import json
import threading
import time
import weakref

from ..utils.logging import logger
from .config import PeerFailureError

PEER_OK = "ok"
PEER_SLOW = "slow"
PEER_DEAD = "dead"

# synthetic "peer" name under which continuous heartbeat-TRANSPORT
# failure is reported: the coordination service lives on process 0, so
# an unreachable store is itself a (very likely) peer failure
COORDINATOR = "<coordination-service>"

_KV_PREFIX = "ds_elastic/hb"

# checkpointing's commit-barrier failure path asks the live monitor (if
# any) which peers look stale — "record which peer was absent"
_active_monitor_ref = None


def active_monitor():
    """The most recently started PeerHealthMonitor, or None."""
    ref = _active_monitor_ref
    return ref() if ref is not None else None


def suspect_peers():
    """Names of peers the active monitor considers slow/dead (empty
    when no monitor runs) — used to annotate barrier timeouts."""
    monitor = active_monitor()
    if monitor is None:
        return []
    return [name for name, st in monitor.peer_status().items()
            if st["status"] != PEER_OK]


class InMemoryTransport:
    """Process-local heartbeat store: the single-host stand-in (and the
    seam the fault injector's simulated peers publish through)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._beats = {}

    def publish(self, peer, payload):
        with self._lock:
            self._beats[str(peer)] = dict(payload)

    def read_all(self):
        with self._lock:
            return {k: dict(v) for k, v in self._beats.items()}

    def discard(self, peer):
        """Drop a key (best-effort; absent is fine). The KV-page
        handoff channel retires consumed offer/ack slots through this
        so a long-lived serving split cannot grow the store without
        bound."""
        with self._lock:
            self._beats.pop(str(peer), None)


class CoordinationTransport:
    """Heartbeats over the jax.distributed coordination-service KV store
    (the same client `utils.distributed.barrier` uses).

    Newer jax clients allow overwriting a key (`allow_overwrite=True`);
    older ones are append-only, so each publish falls back to a
    serial-suffixed key and reads take the highest serial per peer."""

    def __init__(self, client, prefix=_KV_PREFIX):
        self._client = client
        self._prefix = prefix
        self._overwrite = True   # optimistic; downgraded on TypeError
        self._can_delete = True
        self._warned_growth = False

    def publish(self, peer, payload):
        value = json.dumps(payload)
        key = f"{self._prefix}/{peer}"
        if self._overwrite:
            try:
                self._client.key_value_set(key, value,
                                           allow_overwrite=True)
                return
            except TypeError:       # old client: append-only store
                self._overwrite = False
        serial = payload["serial"]
        self._client.key_value_set(f"{key}/{serial}", value)
        # the fallback would otherwise leak one key per beat forever
        # (and read_all rescans them all every poll): best-effort delete
        # of the key this one supersedes
        if self._can_delete and serial > 1:
            try:
                self._client.key_value_delete(f"{key}/{serial - 1}")
            except AttributeError:
                self._can_delete = False
                if not self._warned_growth:  # pragma: no cover - old jax
                    self._warned_growth = True
                    logger.warning(
                        "heartbeat transport: this jax client supports "
                        "neither key overwrite nor delete — the "
                        "coordination-service heartbeat keys grow by "
                        "one per peer per interval for the job lifetime")
            except Exception:        # already gone / service hiccup
                pass

    def read_all(self):
        try:
            entries = self._client.key_value_dir_get(self._prefix)
        except Exception:  # pragma: no cover - no beats published yet
            return {}
        beats = {}
        for key, value in entries:
            try:
                payload = json.loads(value)
            except (TypeError, ValueError):  # pragma: no cover
                continue
            peer = key[len(self._prefix):].strip("/").split("/")[0]
            prev = beats.get(peer)
            if prev is None or payload.get("serial", 0) >= \
                    prev.get("serial", 0):
                beats[peer] = payload
        return beats

    def discard(self, peer):
        """Best-effort delete of one key (absent / no-delete-support
        clients are fine) — the handoff channel's slot retirement."""
        if not self._can_delete:
            return
        try:
            self._client.key_value_delete(f"{self._prefix}/{peer}")
        except AttributeError:   # pragma: no cover - old jax client
            self._can_delete = False
        except Exception:        # already gone / service hiccup
            pass


class _SimulatedPeer:
    """A fake peer the monitor itself keeps alive each poll — the
    single-host handle `peer_death`/`slow_peer` faults act on."""

    def __init__(self, name):
        self.name = name
        self.alive = True
        self.delay_s = 0.0         # publish at most once per delay_s
        self.serial = 0
        self._last_pub = None


class PeerHealthMonitor:
    """Publish-and-observe heartbeat loop with per-peer staleness
    escalation. Thread-hosted in production (`start()`); every decision
    lives in `poll_once(now)` so tests drive it with a fake clock."""

    def __init__(self, self_name, peers=(), interval_s=5.0,
                 warn_after_s=15.0, fail_after_s=60.0, transport=None,
                 clock=time.monotonic, step_fn=None):
        self.self_name = str(self_name)
        self.interval_s = float(interval_s)
        self.warn_after_s = float(warn_after_s)
        self.fail_after_s = float(fail_after_s)
        self.transport = transport if transport is not None \
            else InMemoryTransport()
        self._clock = clock
        # step_fn feeds the published payload (weakly bound by the
        # engine: lambda over a weakref) — peers' dashboards can see how
        # far each host got, and the supervisor's steps-lost accounting
        # reads it from the progress the payload mirrors
        self._step_fn = step_fn or (lambda: -1)

        self._lock = threading.Lock()
        self._serial = 0
        self._last_publish = None
        # name -> {"serial", "step", "seen": local time the serial last
        # advanced, "status"}
        self._peers = {str(p): None for p in peers if str(p) !=
                       self.self_name}
        self._simulated = {}
        self.failed = {}             # name -> staleness at death
        self.warned = set()
        # peer name -> slice name (docs/multislice.md): when set, the
        # SLICE becomes the unit of staleness escalation — one dead
        # host breaks its slice's ICI mesh, so the whole slice is lost
        self._slice_map = {}
        # quantitative per-host step skew from the fleet probe
        # (runtime/fleet.py note_skew): whole-dict swaps, read lock-free
        # from the poll thread so escalation logs can cite it
        self._skew_behind_ms = {}
        self._skew_steps = {}
        self.transport_errors = 0
        self._transport_fail_since = None
        self._first_poll = None      # first-beat grace starts here
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        global _active_monitor_ref
        if self._thread is not None:
            return self
        _active_monitor_ref = weakref.ref(self)
        self_ref = weakref.ref(self)

        def loop():
            while True:
                monitor = self_ref()
                if monitor is None:
                    return
                stop, poll = monitor._stop, monitor._poll_period()
                monitor.poll_once()
                del monitor          # don't pin across the wait
                if stop.wait(poll):
                    return

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ds-peer-health")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _poll_period(self):
        # observe a few times per publish interval so a peer crossing
        # warn/fail thresholds is noticed promptly
        return max(min(self.interval_s / 2.0, 1.0), 0.05)

    # -- fleet skew probe (runtime/fleet.py) -------------------------------

    def note_skew(self, behind_ms_by_peer, behind_steps_by_peer):
        """Record the fleet probe's quantitative verdict: per-host EMA
        of step-time lateness behind the fleet median (ms) and the
        consecutive steps each host has spent past the slow threshold.
        Whole-dict swaps (atomic under the GIL) — the poll thread reads
        without taking the monitor lock, so `skew_context` is safe from
        inside `_observe`."""
        self._skew_behind_ms = {str(k): float(v)
                                for k, v in behind_ms_by_peer.items()}
        self._skew_steps = {str(k): int(v)
                            for k, v in behind_steps_by_peer.items()}

    def skew_context(self, name):
        """Human-readable skew citation for one peer ("host 3 is
        180ms/step behind the median for 50 consecutive steps"), or
        None when the probe has nothing quantitative on it — the slow
        escalation and the hang watchdog's LOCAL-vs-peer verdict cite
        this instead of a staleness guess."""
        name = str(name)
        behind = self._skew_behind_ms.get(name)
        if behind is None or behind <= 0:
            return None
        steps = self._skew_steps.get(name, 0)
        return (f"host {name} is {behind:.0f}ms/step behind the median "
                f"for {steps} consecutive steps")

    # -- fault-injection hooks (single-host simulated peers) ---------------

    def ensure_simulated_peer(self, name):
        name = str(name)
        with self._lock:
            if name not in self._simulated:
                self._simulated[name] = _SimulatedPeer(name)
                self._peers.setdefault(name, None)
        return name

    def inject_peer_death(self, name):
        """The simulated peer stops heartbeating — indistinguishable,
        to the observer, from the host dying."""
        sim = self._simulated.get(str(name))
        if sim is None:
            raise KeyError(f"no simulated peer {name!r} registered")
        sim.alive = False
        logger.warning(f"fault injection: simulated peer {name} died "
                       f"(heartbeats stop)")

    def inject_slow_peer(self, name, delay_s):
        """The simulated peer heartbeats at most once per `delay_s` —
        a wedged-but-alive host (straggler / thrashing)."""
        sim = self._simulated.get(str(name))
        if sim is None:
            raise KeyError(f"no simulated peer {name!r} registered")
        sim.delay_s = float(delay_s)
        logger.warning(f"fault injection: simulated peer {name} slowed "
                       f"to one heartbeat per {delay_s:.1f}s")

    def simulated_delays(self):
        """{name: delay_s} of the LIVE simulated peers — the fleet skew
        probe's single-host gather reads a `slow_peer` fault's delay as
        that host's per-step arrival lateness."""
        with self._lock:
            return {name: sim.delay_s
                    for name, sim in self._simulated.items() if sim.alive}

    # -- slice granularity (docs/multislice.md) ----------------------------

    def set_slice_map(self, peer_to_slice):
        """Promote escalation to SLICE granularity: map each heartbeat
        peer to its slice. Unmapped peers (and the COORDINATOR
        pseudo-peer) keep host-granular semantics — their loss is never
        a slice loss."""
        self._slice_map = {str(p): str(s)
                           for p, s in dict(peer_to_slice).items()}

    def slice_of(self, name):
        return self._slice_map.get(str(name))

    def peers_in_slice(self, slice_name):
        return sorted(p for p, s in self._slice_map.items()
                      if s == str(slice_name))

    @property
    def failed_slices(self):
        """Slice names with >= 1 dead member. A single dead host is a
        hole in its slice's ICI mesh: the slice's collectives cannot
        complete, so the slice — not the host — is the failure unit."""
        return sorted({self._slice_map[p] for p in self.failed
                       if p in self._slice_map})

    def slice_status(self, now=None):
        """{slice: {"status", "peers", "dead"}} — "ok" only when every
        member is ok; any dead member makes the slice "dead"."""
        per_peer = self.peer_status(now)
        out = {}
        for peer, sname in self._slice_map.items():
            ent = out.setdefault(sname, {"status": "ok", "peers": [],
                                         "dead": []})
            ent["peers"].append(peer)
            status = (per_peer.get(peer) or {}).get("status", "unknown")
            if peer in self.failed or status == "dead":
                ent["status"] = "dead"
                ent["dead"].append(peer)
            elif status == "slow" and ent["status"] == "ok":
                ent["status"] = "slow"
        for ent in out.values():
            ent["peers"].sort()
            ent["dead"].sort()
        return out

    def kill_slice(self, slice_name):
        """Fault-injection hook: stop the heartbeats of every SIMULATED
        member of `slice_name` (the `slice_kill` fault kind). Raises if
        the slice has no simulated members — a silently inert kill
        would pass the chaos drill without testing anything."""
        members = self.peers_in_slice(slice_name)
        sims = [p for p in members if p in self._simulated]
        if not sims:
            raise KeyError(
                f"slice {slice_name!r} has no simulated peers "
                f"registered (members: {members})")
        for p in sims:
            self.inject_peer_death(p)
        logger.warning(f"fault injection: slice {slice_name} killed "
                       f"({len(sims)} simulated peer(s))")

    # -- the observable core ----------------------------------------------

    def poll_once(self, now=None):
        """One publish-and-observe turn. Returns the current
        {peer: status-dict} view.

        Transport errors (the coordination service going unreachable —
        most likely because the host backing it died) must not kill the
        monitor thread and silently disable detection: they are caught,
        counted, and after ``fail_after_s`` of CONTINUOUS failure the
        coordination service itself is declared a dead peer (the
        escalation path then runs exactly as for any other peer)."""
        now = self._clock() if now is None else now
        if self._first_poll is None:
            self._first_poll = now
        try:
            self._publish_self(now)
            self._publish_simulated(now)
            self._observe(now)
        except Exception as e:
            self._note_transport_error(now, e)
        else:
            self._transport_fail_since = None
        return self.peer_status()

    def _note_transport_error(self, now, exc):
        self.transport_errors += 1
        if self._transport_fail_since is None:
            self._transport_fail_since = now
            logger.warning(
                f"peer health: heartbeat transport error "
                f"({type(exc).__name__}: {exc}) — the coordination "
                f"service may be unreachable; escalating to peer "
                f"failure after {self.fail_after_s:.1f}s of continuous "
                f"failure")
            return
        outage = now - self._transport_fail_since
        if outage > self.fail_after_s and COORDINATOR not in self.failed:
            self.failed[COORDINATOR] = outage
            logger.error(
                f"peer health: heartbeat transport unreachable for "
                f"{outage:.1f}s (> fail_after_s={self.fail_after_s:.1f})"
                f" — declaring the coordination service (process 0) "
                f"DEAD")

    def _publish_self(self, now):
        if self._last_publish is not None and \
                now - self._last_publish < self.interval_s:
            return
        self._last_publish = now
        self._serial += 1
        try:
            step = int(self._step_fn())
        except Exception:   # engine mid-teardown: keep heartbeating
            step = -1
        self.transport.publish(self.self_name,
                               {"serial": self._serial, "step": step})

    def _publish_simulated(self, now):
        with self._lock:
            sims = list(self._simulated.values())
        for sim in sims:
            if not sim.alive:
                continue
            period = max(self.interval_s, sim.delay_s)
            if sim._last_pub is not None and \
                    now - sim._last_pub < period:
                continue
            sim._last_pub = now
            sim.serial += 1
            self.transport.publish(sim.name,
                                   {"serial": sim.serial, "step": -1})

    def _observe(self, now):
        beats = self.transport.read_all()
        with self._lock:
            # adopt peers discovered from the store (a regrown topology
            # may add ranks the constructor never listed)
            for name in beats:
                if name != self.self_name:
                    self._peers.setdefault(name, None)
            for name in list(self._peers):
                beat = beats.get(name)
                state = self._peers[name]
                if beat is None and state is None:
                    # peer has NEVER published. The grace is BOUNDED by
                    # the same thresholds, measured from the monitor's
                    # first poll: a host dead at bring-up must escalate
                    # like any other (unbounded grace would leave it
                    # permanently 'ok' and misdiagnose the resulting
                    # collective hang as local).
                    silent = now - self._first_poll
                    if silent > self.fail_after_s:
                        self._peers[name] = {
                            "serial": -1, "step": -1,
                            "seen": self._first_poll,
                            "status": PEER_DEAD}
                        self.failed[name] = silent
                        logger.error(
                            f"peer health: peer {name} NEVER published "
                            f"a heartbeat in {silent:.1f}s (> "
                            f"fail_after_s={self.fail_after_s:.1f}) — "
                            f"declaring it DEAD (died during bring-up?)")
                    elif silent > self.warn_after_s and \
                            name not in self.warned:
                        self.warned.add(name)
                        logger.warning(
                            f"peer health: peer {name} has not "
                            f"published its first heartbeat after "
                            f"{silent:.1f}s — slow bring-up or dead; "
                            f"escalating at {self.fail_after_s:.1f}s")
                    continue
                if state is None or (beat is not None and
                                     beat["serial"] > state["serial"]):
                    if state is not None and \
                            state["status"] == PEER_DEAD:
                        # dead is STICKY: by the time a declared-dead
                        # peer heartbeats again the collective world is
                        # already torn — the escalation (restart) must
                        # proceed, not be raced away by a revival
                        continue
                    if state is not None and \
                            state["status"] == PEER_SLOW:
                        logger.info(
                            f"peer health: peer {name} recovered after "
                            f"{now - state['seen']:.1f}s of silence")
                    self._peers[name] = {
                        "serial": beat["serial"],
                        "step": beat.get("step", -1),
                        "seen": now, "status": PEER_OK}
                    continue
                staleness = now - state["seen"]
                if staleness > self.fail_after_s:
                    if state["status"] != PEER_DEAD:
                        state["status"] = PEER_DEAD
                        self.failed[name] = staleness
                        logger.error(
                            f"peer health: peer {name} heartbeat stale "
                            f"for {staleness:.1f}s (> fail_after_s="
                            f"{self.fail_after_s:.1f}) — declaring it "
                            f"DEAD; last seen at step {state['step']}")
                elif staleness > self.warn_after_s:
                    if state["status"] == PEER_OK:
                        state["status"] = PEER_SLOW
                        self.warned.add(name)
                        # cite the fleet probe's quantitative skew when
                        # available: "slow" backed by measured ms/step,
                        # not just a staleness guess
                        skew = self.skew_context(name)
                        logger.warning(
                            f"peer health: peer {name} heartbeat stale "
                            f"for {staleness:.1f}s (> warn_after_s="
                            f"{self.warn_after_s:.1f}) — slow or "
                            f"wedged; escalating to dead at "
                            f"{self.fail_after_s:.1f}s"
                            + (f" [fleet skew probe: {skew}]" if skew
                               else ""))

    # -- views -------------------------------------------------------------

    def peer_status(self, now=None):
        now = self._clock() if now is None else now
        with self._lock:
            out = {}
            for name, state in self._peers.items():
                if state is None:
                    out[name] = {"status": PEER_OK, "staleness_s": 0.0,
                                 "step": -1}
                else:
                    out[name] = {"status": state["status"],
                                 "staleness_s": now - state["seen"],
                                 "step": state["step"]}
            return out

    def max_staleness(self, now=None):
        """Worst peer staleness in seconds (0.0 with no peers) — the
        per-step `Train/Elastic/heartbeat_staleness_s` scalar."""
        status = self.peer_status(now)
        return max((s["staleness_s"] for s in status.values()),
                   default=0.0)

    @property
    def has_failure(self):
        return bool(self.failed)

    def raise_if_failed(self):
        """Main-thread escalation point (engine step boundary): a dead
        peer becomes a typed PeerFailureError for the supervisor."""
        if not self.failed:
            return
        peers = sorted(self.failed)
        staleness = max(self.failed.values())
        raise PeerFailureError(
            f"peer(s) {peers} declared dead (heartbeat stale "
            f"{staleness:.1f}s > fail_after_s={self.fail_after_s:.1f}); "
            f"exiting for a supervised restart",
            peers=peers, staleness_s=staleness)


def build_peer_monitor(params, step_fn=None):
    """Construct the monitor from a validated heartbeat params dict
    (`elasticity.config.parse_heartbeat_block`): coordination-service
    transport when a multi-host client exists, in-memory otherwise."""
    import jax

    from ..utils.distributed import _distributed_client
    from ..utils.kv_retry import wrap_kv_transport
    transport = None
    peers = ()
    if jax.process_count() > 1:
        client = _distributed_client()
        if client is not None:
            # shared retry policy (utils/kv_retry.py): transient KV
            # blips are retried with capped backoff × jitter;
            # PERSISTENT failure still raises into poll_once — the
            # monitor's continuous-outage escalation (declare the
            # coordination service dead after fail_after_s) depends on
            # seeing it, so heartbeats never degrade-to-local
            transport = wrap_kv_transport(
                CoordinationTransport(client), degrade_to_local=False,
                name="peer-health heartbeat")
            peers = [str(i) for i in range(jax.process_count())]
        else:  # pragma: no cover - private-API drift
            logger.warning(
                "elasticity.heartbeat: no coordination client available; "
                "peer heartbeats degrade to process-local (peer failures "
                "will only surface as barrier timeouts)")
    return PeerHealthMonitor(
        self_name=str(jax.process_index()), peers=peers,
        interval_s=params["interval_s"],
        warn_after_s=params["warn_after_s"],
        fail_after_s=params["fail_after_s"],
        transport=transport, step_fn=step_fn)
