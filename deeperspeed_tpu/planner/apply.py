"""Consume a persisted plan inside the engine's config pipeline.

`DeepSpeedConfig` parses the strict ``"planner"`` block, then hands the
RAW param dict here: the plan's resolved config is merged UNDER the
user's explicit keys (a hand-set `prefetch_depth` always beats the
plan — the planner provides defaults, never overrides), before the
zero/activation-checkpointing/quantization blocks parse. A plan emitted
for a different device kind warns by default and raises under
``strict_device_match``.
"""

import os

from ..utils.logging import logger
from .plan import cached_plan, load_plan


def _merge_under(dst, src):
    """Recursively fill `dst` with `src` values for keys the user did
    not set; returns the list of dotted keys the plan contributed."""
    applied = []
    for key, val in src.items():
        if isinstance(val, dict) and isinstance(dst.get(key), dict):
            applied.extend(f"{key}.{sub}"
                           for sub in _merge_under(dst[key], val))
        elif key not in dst:
            dst[key] = val
            applied.append(key)
    return applied


def resolve_plan(planner_cfg, device_kind=None, shape_key=None):
    """The plan a parsed planner block points at, or None. An explicit
    `plan_file` that does not exist raises (a typo'd path silently
    training unplanned is the parse-only-key bug class all over)."""
    path = planner_cfg.get("plan_file")
    if path:
        path = os.path.expanduser(path)
        if not os.path.exists(path):
            from ..runtime.config_utils import DeepSpeedConfigError
            raise DeepSpeedConfigError(
                f"planner.plan_file {path!r} does not exist — emit it "
                f"with ds_plan, or drop the planner block")
        return load_plan(path)
    if device_kind is not None and shape_key is not None:
        return cached_plan(device_kind, shape_key)
    return None


def overlay_plan(param_dict, planner_cfg):
    """Merge the configured plan's resolved config under `param_dict`.

    Returns ``(fingerprint, applied_keys)`` — the applied plan's
    fingerprint plus the dotted keys the plan (not the user)
    contributed, or ``(None, [])`` when the block is disabled or points
    at nothing. Called BEFORE the schedule/offload/quantization blocks
    parse, so the merged keys go through the exact same strict
    validation a hand-written config would; the applied-keys list is
    what lets the engine tell a plan-provided knob (advisory — may
    degrade) from a user-set one (contractual — must raise)."""
    if not planner_cfg or not planner_cfg.get("enabled", True):
        return None, []
    plan = resolve_plan(planner_cfg)
    if plan is None:
        return None, []

    try:
        from ..ops.autotune import _device_kind
        here = _device_kind()
    except Exception:  # noqa: BLE001 - backendless config parse
        here = "unknown"
    if plan.device_kind not in ("unknown", here):
        msg = (f"planner: plan {plan.fingerprint} was emitted for "
               f"device kind {plan.device_kind!r}, this host runs "
               f"{here!r}")
        if planner_cfg.get("strict_device_match"):
            from ..runtime.config_utils import DeepSpeedConfigError
            raise DeepSpeedConfigError(
                f"{msg} (planner.strict_device_match is set — re-plan "
                f"on this device kind with ds_plan)")
        logger.warning(f"{msg}; applying anyway (its measured ranking "
                       f"may not transfer)")

    applied = _merge_under(param_dict, plan.config)
    logger.info(f"planner: applied plan {plan.fingerprint} "
                f"({plan.payload.get('chosen', '?')}); plan-provided "
                f"keys: {applied or 'none (user config covers all)'}")
    return plan.fingerprint, applied
