"""Profile-guided schedule planner (ROADMAP item 3; DeepCompile's
thesis applied to this repo): one cost-model-driven search over the
whole schedule knob space — `zero_optimization.schedule` {mode,
prefetch_depth, bucket_mb, group_layers, remat}, activation
checkpointing, offload tier, quantization recipe, per-kernel block
geometries — replacing per-knob hand-tuning.

Pipeline: analytic cost model (`cost_model`) prunes the grid →
measured probe ladder (`search`, riding `ops.autotune.ladder_pick`'s
measure-once discipline) ranks the survivors → the winning plan is
emitted and persisted (`plan`) per (device kind, model shape) → the
engine consumes it through the `"planner"` config block (`apply`) and
`ds_plan` / `ds_report --json` surface it. See docs/planner.md.
"""

from .cost_model import Candidate, ModelShape  # noqa: F401
from .plan import (Plan, cached_plan, latest_plan,  # noqa: F401
                   latest_plan_fingerprint, load_plan, plan_cache_dir)
from .search import build_plan, enumerate_candidates  # noqa: F401
from .apply import overlay_plan  # noqa: F401
