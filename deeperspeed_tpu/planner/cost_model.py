"""Analytic cost model for the schedule planner.

Prices a candidate schedule in seconds and bytes BEFORE anything runs:
compute time from the per-device-kind peak-flops table
(`profiling/hardware.py`), collective time from a bytes/bandwidth model
over the explicit schedule's `plan_buckets` bucketing math
(`parallel/schedule.py` — the SAME function the runtime uses to split
layer rows, so the model and the executed schedule can never disagree
about bucket counts), memory from a byte ledger screened against
`hbm_bytes_limit`.

This is a RANKING model, not a simulator: absolute seconds are wrong
everywhere, but the relative ordering of candidates is what prunes the
combinatorial knob space to the small measured ladder (DeepCompile's
argument: plan over a profiled cost model, then verify the survivors on
real steps). Every fudge factor lives in a named module constant.
"""

from dataclasses import dataclass

from ..parallel.schedule import plan_buckets
from ..profiling.hardware import (COLLECTIVE_LATENCY_S,
                                  ici_bandwidth_per_chip,
                                  peak_flops_per_chip)

# Achievable fraction of peak for dense bf16 transformer compute — the
# repo's measured headline MFU band (BENCH_r05: 0.607 at 125m).
BASE_EFFICIENCY = 0.6

# Full-remat recomputes the forward inside the backward: fwd(1) +
# bwd(2) + recompute(1) over the plain fwd+bwd(3).
REMAT_COMPUTE_FACTOR = 4.0 / 3.0

# Effective FFN-matmul speedup of the delayed-scaling quantized recipes
# (ops/pallas/quant_matmul): int8 doubles MXU issue rate on the FFN
# ~2/3 of the flops, derated for quant/dequant overhead. CPU and
# unsupported generations fall back to XLA emulation — the probe phase
# (not this table) is what catches that.
QUANT_FFN_FACTOR = {None: 1.0, "int8": 0.82, "fp8": 0.85}

# Fraction of collective time XLA's GSPMD scheduling is assumed to hide
# behind compute (no explicit prefetch window to reason about).
GSPMD_OVERLAP = 0.5

# Host<->device link for the offload tiers (PCIe-class, bytes/s).
HOST_LINK_BANDWIDTH = 32e9

# Resident-bytes fudge: runtime buffers, fragmentation (matches the
# `memory_feasible` default safety margin).
MEMORY_SAFETY = 0.92

# Per-layer activation bytes ~= ACT_BYTES_PER_ELEM * batch * seq *
# hidden without remat (attention scores and MLP intermediates
# included); full remat keeps only layer-boundary residuals.
ACT_BYTES_PER_ELEM = 16
ACT_BYTES_PER_ELEM_REMAT = 2


@dataclass(frozen=True)
class ModelShape:
    """The (model geometry, per-chip workload) a plan is keyed on."""
    num_layers: int
    hidden_size: int
    num_heads: int
    seq_len: int
    vocab_size: int
    batch_per_chip: int
    param_count: int = 0        # 0 = estimate from the geometry

    @property
    def params(self):
        if self.param_count:
            return int(self.param_count)
        # embed + 12 h^2 per layer (attn 4h^2 + mlp 8h^2) + final norm
        return (self.vocab_size * self.hidden_size
                + 12 * self.num_layers * self.hidden_size ** 2)

    @property
    def layer_params(self):
        """Params that live inside the layer stack (what the explicit
        schedule gathers per layer; embeddings sit outside the loop)."""
        return 12 * self.num_layers * self.hidden_size ** 2

    def key(self):
        """Stable identity for plan-cache filenames."""
        return (f"l{self.num_layers}-h{self.hidden_size}"
                f"-a{self.num_heads}-s{self.seq_len}"
                f"-v{self.vocab_size}-b{self.batch_per_chip}"
                f"-p{self.params}")

    def flops_per_token(self):
        return (6 * self.params
                + 12 * self.num_layers * self.hidden_size * self.seq_len)


@dataclass(frozen=True)
class Candidate:
    """One point of the schedule knob space."""
    mode: str = "gspmd"            # zero_optimization.schedule.mode
    prefetch_depth: int = 2
    bucket_mb: float = 32.0
    group_layers: int = 4
    remat: bool = False
    offload: str = "none"          # none | cpu | nvme
    quant_ffn: str = None          # None | int8 | fp8

    def label(self):
        bits = [self.mode, f"p{self.prefetch_depth}",
                f"b{int(self.bucket_mb)}", f"g{self.group_layers}"]
        if self.remat:
            bits.append("remat")
        if self.offload != "none":
            bits.append(f"off:{self.offload}")
        if self.quant_ffn:
            bits.append(self.quant_ffn)
        return "/".join(bits)


def hardware_profile(device_kind, hbm_limit=None):
    """Resolve the cost-model inputs for a device-kind string."""
    return {
        "device_kind": device_kind,
        "peak_flops": peak_flops_per_chip(device_kind),
        "ici_bandwidth": ici_bandwidth_per_chip(device_kind),
        "hbm_limit": hbm_limit,
    }


def compute_time_s(cand, shape, hw):
    """Per-chip dense compute time for one step."""
    tokens = shape.batch_per_chip * shape.seq_len
    flops = tokens * shape.flops_per_token() * 3  # fwd + 2x bwd
    if cand.remat:
        flops *= REMAT_COMPUTE_FACTOR
    flops *= QUANT_FFN_FACTOR.get(cand.quant_ffn, 1.0)
    return flops / (hw["peak_flops"] * BASE_EFFICIENCY)


def collective_time_s(cand, shape, hw, world):
    """Exposed (non-overlapped) collective seconds for one step.

    Explicit mode reasons per layer group: each group's bucketed
    all-gather (bucket count from the runtime's own `plan_buckets`) can
    hide behind the compute of the `prefetch_depth` groups ahead of it;
    whatever does not fit that window is exposed. The backward
    reduce-scatters mirror the gathers. GSPMD mode prices the same
    bytes at a flat assumed overlap.
    """
    if world <= 1:
        return 0.0
    itemsize = 2  # bf16 compute params
    layer_elems = shape.layer_params // max(1, shape.num_layers)
    shard_elems = max(1, layer_elems // world)
    per_layer_bytes = layer_elems * itemsize * (world - 1) / world
    wire_s_per_layer = per_layer_bytes / hw["ici_bandwidth"]

    if cand.mode != "explicit":
        total = 2 * shape.num_layers * (wire_s_per_layer
                                        + COLLECTIVE_LATENCY_S)
        return total * (1.0 - GSPMD_OVERLAP)

    buckets = plan_buckets(shard_elems, itemsize,
                           int(cand.bucket_mb * (1 << 20)))
    n_buckets_per_layer = max(1, len(buckets))
    group = max(1, int(cand.group_layers))
    n_groups = max(1, -(-shape.num_layers // group))
    per_group_gather = group * (
        n_buckets_per_layer * COLLECTIVE_LATENCY_S + wire_s_per_layer)
    per_group_compute = compute_time_s(cand, shape, hw) / n_groups
    window = cand.prefetch_depth * per_group_compute
    exposed = max(0.0, per_group_gather - window)
    # first group's gather is cold (nothing to hide behind); gathers and
    # the mirrored reduce-scatters each expose their overflow
    return per_group_gather + 2 * (n_groups - 1) * exposed


def offload_time_s(cand, shape, hw, world):
    """Exposed host-link seconds when a tier holds the param/optimizer
    rows off-device: each step streams the shard down and the grad rows
    back, double-buffered prefetch hides part of it."""
    if cand.offload == "none":
        return 0.0
    shard_bytes = shape.params * 2 / max(1, world)
    transfer = 2 * shard_bytes / HOST_LINK_BANDWIDTH
    return transfer / (1 + max(0, cand.prefetch_depth))


def memory_bytes(cand, shape, world, stage=3):
    """Estimated resident HBM bytes per chip for the candidate."""
    p = shape.params
    itemsize = 2
    param_bytes = p * itemsize
    if stage >= 3:
        resident_params = param_bytes / world
        layer_bytes = (shape.layer_params // max(1, shape.num_layers)
                       ) * itemsize
        # gathered working set: the in-flight window of layer groups
        window_groups = 1 + max(0, cand.prefetch_depth)
        resident_params += (window_groups * cand.group_layers
                            * layer_bytes)
    else:
        resident_params = param_bytes
    grad_bytes = param_bytes / (world if stage >= 2 else 1)
    opt_bytes = 8 * p / (world if stage >= 1 else 1)
    if cand.offload != "none":
        # rows rest tier-side; on-chip cost is the staging buffers
        opt_bytes = 0
        if stage >= 3:
            resident_params = ((1 + max(0, cand.prefetch_depth))
                               * cand.group_layers
                               * (shape.layer_params
                                  // max(1, shape.num_layers)) * itemsize)
    act_elem = (ACT_BYTES_PER_ELEM_REMAT if cand.remat
                else ACT_BYTES_PER_ELEM)
    act_bytes = (shape.batch_per_chip * shape.seq_len * shape.hidden_size
                 * act_elem * shape.num_layers)
    return int(resident_params + grad_bytes + opt_bytes + act_bytes)


def memory_feasible_analytic(cand, shape, world, hbm_limit, stage=3):
    """The analytic screen: None budget never blocks a candidate (the
    same contract as `ops.autotune.memory_feasible`)."""
    if hbm_limit is None:
        return True
    return memory_bytes(cand, shape, world, stage) <= \
        hbm_limit * MEMORY_SAFETY


def step_time_s(cand, shape, hw, world):
    """Total analytic step seconds: compute + exposed collectives +
    exposed offload traffic."""
    return (compute_time_s(cand, shape, hw)
            + collective_time_s(cand, shape, hw, world)
            + offload_time_s(cand, shape, hw, world))
