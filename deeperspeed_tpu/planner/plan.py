"""Plan objects: the emitted, persisted result of a planner run.

A plan is the FULL resolved config the search settled on — the
`zero_optimization.schedule` knobs, activation-checkpointing policy,
offload tier + buffer counts, quantization recipe, and the per-kernel
block geometries — persisted per (device kind, model shape) the way the
autotune cache is keyed per (key, device kind). `ds_plan` writes these,
`ds_report --json` surfaces the newest fingerprint, and the engine
consumes one through the validated ``"planner"`` config block
(`runtime/config.py:parse_planner_block`).
"""

import hashlib
import json
import os

PLAN_VERSION = 1
PLAN_CACHE_ENV = "DS_PLAN_CACHE"
_DEFAULT_CACHE = os.path.join("~", ".cache", "deeperspeed_tpu", "plans")


def plan_cache_dir(cache_dir=None):
    return os.path.expanduser(
        cache_dir or os.environ.get(PLAN_CACHE_ENV) or _DEFAULT_CACHE)


def _slug(text):
    return "".join(c if c.isalnum() or c in "-._" else "-"
                   for c in str(text)) or "unknown"


def plan_fingerprint(payload):
    """Short content hash over the canonical payload (fingerprint field
    excluded, so re-fingerprinting a loaded plan is stable)."""
    body = {k: v for k, v in payload.items() if k != "fingerprint"}
    blob = json.dumps(body, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


class Plan:
    """Thin, dict-backed wrapper; `payload` is exactly the JSON file."""

    def __init__(self, payload):
        self.payload = dict(payload)
        self.payload.setdefault("version", PLAN_VERSION)
        self.payload["fingerprint"] = plan_fingerprint(self.payload)

    @property
    def fingerprint(self):
        return self.payload["fingerprint"]

    @property
    def device_kind(self):
        return self.payload.get("device_kind", "unknown")

    @property
    def config(self):
        """The resolved config overlay (see apply.overlay_plan)."""
        return self.payload.get("config", {})

    @property
    def probed(self):
        return bool(self.payload.get("probed"))

    def cache_path(self, cache_dir=None):
        shape_key = self.payload.get("shape_key", "unknown")
        return os.path.join(
            plan_cache_dir(cache_dir),
            f"plan-{_slug(self.device_kind)}-{_slug(shape_key)}.json")

    def save(self, path=None, cache_dir=None):
        """Atomic write (tmp + rename): a crashed `ds_plan` must not
        leave a torn JSON where the engine will read it."""
        path = path or self.cache_path(cache_dir)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.payload, f, indent=2, sort_keys=True,
                      default=str)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def to_json(self):
        return json.dumps(self.payload, indent=2, sort_keys=True,
                          default=str)


def load_plan(path):
    """Load + re-fingerprint a plan file; a payload whose recorded
    fingerprint disagrees with its content raises (a hand-edited plan
    must be re-emitted through `ds_plan`, not trusted silently)."""
    with open(path) as f:
        payload = json.load(f)
    recorded = payload.get("fingerprint")
    plan = Plan(payload)
    if recorded and recorded != plan.fingerprint:
        raise ValueError(
            f"plan file {path} fingerprint mismatch: recorded "
            f"{recorded!r}, content hashes to {plan.fingerprint!r} — "
            f"re-emit it with ds_plan instead of hand-editing")
    return plan


def cached_plan(device_kind, shape_key, cache_dir=None):
    """The persisted plan for (device kind, model shape), or None —
    the warm-cache path: a hit performs zero probes."""
    path = os.path.join(
        plan_cache_dir(cache_dir),
        f"plan-{_slug(device_kind)}-{_slug(shape_key)}.json")
    if not os.path.exists(path):
        return None
    try:
        return load_plan(path)
    except Exception:  # noqa: BLE001 - torn/stale cache = replan
        return None


def latest_plan(cache_dir=None):
    """Newest persisted plan in the cache (what `ds_report --json`
    surfaces), or None."""
    root = plan_cache_dir(cache_dir)
    try:
        files = [os.path.join(root, f) for f in os.listdir(root)
                 if f.startswith("plan-") and f.endswith(".json")]
    except OSError:
        return None
    for path in sorted(files, key=os.path.getmtime, reverse=True):
        try:
            return load_plan(path)
        except Exception:  # noqa: BLE001 - skip torn files
            continue
    return None


def latest_plan_fingerprint(cache_dir=None):
    plan = latest_plan(cache_dir)
    return plan.fingerprint if plan is not None else None
