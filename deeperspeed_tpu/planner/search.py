"""The planner's search driver: enumerate → analytic prune → probe.

Subsumes the per-kernel pickers' search discipline behind one driver:
the combinatorial schedule space (mode × prefetch_depth × bucket_mb ×
group_layers × remat × offload tier × quant recipe) is scored by the
analytic cost model and memory-screened down to a small ladder, then
the surviving rungs are ranked on real measured steps through the SAME
`ladder_pick` spine the kernel autotuners run on — so the planner
inherits the Autotuner's measure-once cache, the multi-host
deterministic degrade, and the interpret-mode / `DS_TPU_AUTOTUNE=0`
analytic-only fallbacks for free.
"""

import itertools

from ..ops.autotune import (Autotuner, autotune_enabled, hbm_bytes_limit,
                            ladder_pick)
from . import cost_model as cm
from .plan import PLAN_VERSION, Plan, cached_plan

# The knob grid the analytic model prunes. Small on purpose: the model
# is cheap (microseconds per candidate) but the grid must stay
# readable/loggable; axes with measured flat spots are thinned.
# DEFAULT-FIRST ordering on every axis: the analytic ladder's stable
# sort resolves exact ties (e.g. world=1, where all collective terms
# are zero) toward the hand-tuned BENCH_r05 defaults, so an
# analytic-only plan never regresses the known-good config on axes the
# model cannot separate — only a measured probe may move off them.
MODES = ("explicit", "gspmd")
PREFETCH_DEPTHS = (2, 1, 4)
BUCKET_MBS = (32.0, 8.0, 128.0)
GROUP_LAYERS = (4, 1, 2)
REMATS = (False, True)
OFFLOADS = ("none", "cpu")
# Quantized FFN recipes are OPT-IN at the build_plan level
# (allow_quant): analytically they always look faster, but they change
# training numerics — a plan should only flip them on when the caller
# asked to consider them (ds_plan --quant) and ideally probed them.
QUANT_FFNS = (None, "int8")

# How many analytic survivors graduate to the measured probe ladder.
DEFAULT_TOP_K = 4

# A dedicated tuner instance: plan probes are whole train steps, one
# timed iteration is plenty (the kernel tuners' 3 would triple an
# already-expensive probe phase).
_plan_tuner = Autotuner(warmup=1, iters=1)


def enumerate_candidates(allow_offload=True, allow_quant=True):
    """The full grid as `Candidate`s. GSPMD mode has no
    prefetch/bucket/group knobs — those collapse to one representative
    per (remat, offload, quant) so the grid carries no dead duplicates."""
    out = []
    offloads = OFFLOADS if allow_offload else ("none",)
    quants = QUANT_FFNS if allow_quant else (None,)
    for mode in MODES:
        knobs = (itertools.product(PREFETCH_DEPTHS, BUCKET_MBS,
                                   GROUP_LAYERS)
                 if mode == "explicit" else ((2, 32.0, 4),))
        for (pf, bmb, gl), remat, off, q in itertools.product(
                knobs, REMATS, offloads, quants):
            out.append(cm.Candidate(mode=mode, prefetch_depth=pf,
                                    bucket_mb=bmb, group_layers=gl,
                                    remat=remat, offload=off,
                                    quant_ffn=q))
    return out


def analytic_ladder(shape, hw, world, stage=3, top_k=DEFAULT_TOP_K,
                    candidates=None, aot_screen=None):
    """Score the grid, drop memory-infeasible points, return the
    `top_k` cheapest as (candidate, scores) rungs, fastest first.

    `aot_screen`, when given, is `candidate -> bool` running the
    caller's `memory_feasible` AOT compile over abstract shapes —
    the concrete screen on top of the analytic byte ledger."""
    rungs = []
    for cand in (candidates or enumerate_candidates()):
        if not cm.memory_feasible_analytic(cand, shape, world,
                                           hw["hbm_limit"], stage):
            continue
        scores = {
            "compute_s": cm.compute_time_s(cand, shape, hw),
            "collective_s": cm.collective_time_s(cand, shape, hw, world),
            "offload_s": cm.offload_time_s(cand, shape, hw, world),
            "memory_bytes": cm.memory_bytes(cand, shape, world, stage),
        }
        scores["step_s"] = (scores["compute_s"] + scores["collective_s"]
                            + scores["offload_s"])
        rungs.append((cand, scores))
    rungs.sort(key=lambda r: r[1]["step_s"])
    rungs = rungs[:max(1, int(top_k))]
    if aot_screen is not None:
        kept = [(c, s) for c, s in rungs if aot_screen(c)]
        rungs = kept or rungs[:1]
    if not rungs:
        raise ValueError(
            "planner: every candidate failed the memory screen "
            f"(shape {shape.key()}, hbm_limit {hw['hbm_limit']})")
    return rungs


def kernel_geometries(shape):
    """The per-kernel block geometries the plan pins, resolved through
    the kernel pickers' own screening tables (their deterministic
    static picks — never a probe: the plan must be emittable on a
    host with no accelerator). Unavailable kernels record None."""
    import jax.numpy as jnp
    out = {}
    head_dim = max(1, shape.hidden_size // max(1, shape.num_heads))
    attn_shape = (shape.batch_per_chip, shape.seq_len, shape.num_heads,
                  head_dim)
    try:
        from ..ops.autotune import _fitted_flash_candidates
        from ..ops.pallas.flash_attention import (
            _fit_block, flash_attention_supported)
        out["flash_blocks"] = list(_fitted_flash_candidates(
            attn_shape, _fit_block, flash_attention_supported)[0])
    except Exception:  # noqa: BLE001 - kernel unavailable on this host
        out["flash_blocks"] = None
    try:
        from ..ops.autotune import (GMM_BLOCK_CANDIDATES,
                                    _GMM_VMEM_BUDGET, _gmm_itemsize,
                                    gmm_vmem_bytes)
        itemsize = _gmm_itemsize(jnp.bfloat16)
        k_dim, n_dim = shape.hidden_size, 4 * shape.hidden_size
        fits = [c for c in GMM_BLOCK_CANDIDATES
                if max(gmm_vmem_bytes(c[0], c[1], k_dim, itemsize),
                       gmm_vmem_bytes(c[0], c[1], n_dim, itemsize))
                <= _GMM_VMEM_BUDGET]
        out["gmm_blocks"] = list(fits[0] if fits
                                 else GMM_BLOCK_CANDIDATES[-1])
    except Exception:  # noqa: BLE001
        out["gmm_blocks"] = None
    try:
        from ..ops.autotune import (_QMM_VMEM_BUDGET,
                                    QMM_BLOCK_CANDIDATES, _gmm_itemsize,
                                    qmm_vmem_bytes)
        itemsize = _gmm_itemsize(jnp.bfloat16)
        fits = [c for c in QMM_BLOCK_CANDIDATES
                if qmm_vmem_bytes(*c, itemsize=itemsize)
                <= _QMM_VMEM_BUDGET]
        out["qmm_blocks"] = list(fits[0] if fits
                                 else QMM_BLOCK_CANDIDATES[-1])
    except Exception:  # noqa: BLE001
        out["qmm_blocks"] = None
    return out


def candidate_config(cand, stage=3):
    """A candidate's resolved config overlay — what the engine's
    `"planner"` block merges under the user's explicit keys."""
    cfg = {
        "zero_optimization": {
            "stage": stage,
            "schedule": {
                "mode": cand.mode,
                "prefetch_depth": int(cand.prefetch_depth),
                "bucket_mb": float(cand.bucket_mb),
                "group_layers": int(cand.group_layers),
                "remat": bool(cand.remat),
            },
        },
        "activation_checkpointing": {
            "policy": "full" if cand.remat else "none",
        },
    }
    if cand.offload != "none":
        cfg["zero_optimization"]["offload_optimizer"] = {
            "device": cand.offload,
            "buffer_count": 1 + max(0, int(cand.prefetch_depth)),
        }
    if cand.quant_ffn:
        cfg["quantization"] = {"ffn": {"recipe": cand.quant_ffn}}
    return cfg


def probes_measurable(probe, measurable):
    """The planner's degrade verdict, mirroring the kernel pickers:
    no probe callable, `DS_TPU_AUTOTUNE=0`/unset, or interpret-mode
    Pallas (no real accelerator) → analytic-only. Multi-host degrade
    lives in `ladder_pick` itself."""
    if measurable is not None:
        return bool(measurable)
    if probe is None or not autotune_enabled():
        return False
    try:
        from ..ops.pallas.flash_attention import _interpret
        if _interpret():
            return False
    except Exception:  # noqa: BLE001 - kernel module unavailable
        pass
    return True


def build_plan(shape, device_kind=None, world=None, stage=3,
               top_k=DEFAULT_TOP_K, probe=None, measurable=None,
               tuner=None, cache_dir=None, force=False,
               allow_offload=True, allow_quant=False, aot_screen=None,
               hbm_limit=None, save=True):
    """The full planner pipeline; returns a `Plan`.

    1. warm cache: a persisted plan for (device kind, shape) short-
       circuits everything — ZERO probes, zero scoring (`force=True`
       replans);
    2. analytic ladder: enumerate → cost-model score → memory screen →
       `top_k` rungs;
    3. probe phase: `ladder_pick` over the rungs with
       `probe(candidate)` as the measure (timed by the Autotuner with
       `perf_counter` outside traced code); degrades to the analytic
       winner per `probes_measurable`;
    4. emit: resolved config + kernel geometries + analytic scores,
       persisted to the plan cache.
    """
    if device_kind is None:
        from ..ops.autotune import _device_kind
        device_kind = _device_kind()
    if world is None:
        try:
            import jax
            world = len(jax.devices())
        except Exception:  # noqa: BLE001 - backendless planning host
            world = 1
    if not force:
        hit = cached_plan(device_kind, shape.key(), cache_dir)
        if hit is not None:
            return hit

    if hbm_limit is None:
        try:
            hbm_limit = hbm_bytes_limit()
        except Exception:  # noqa: BLE001
            hbm_limit = None
    hw = cm.hardware_profile(device_kind, hbm_limit)
    rungs = analytic_ladder(
        shape, hw, world, stage, top_k,
        candidates=enumerate_candidates(allow_offload=allow_offload,
                                        allow_quant=allow_quant),
        aot_screen=aot_screen)
    scores = {c.label(): s for c, s in rungs}

    can_probe = probes_measurable(probe, measurable)
    chosen = ladder_pick(
        ("plan", device_kind, shape.key(), stage),
        [c for c, _ in rungs],
        probe if probe is not None else (lambda cand: None),
        tuner or _plan_tuner,
        measurable=can_probe)

    payload = {
        "version": PLAN_VERSION,
        "device_kind": device_kind,
        "shape_key": shape.key(),
        "world": int(world),
        "stage": int(stage),
        "model_shape": {
            "num_layers": shape.num_layers,
            "hidden_size": shape.hidden_size,
            "num_heads": shape.num_heads,
            "seq_len": shape.seq_len,
            "vocab_size": shape.vocab_size,
            "batch_per_chip": shape.batch_per_chip,
            "param_count": shape.params,
        },
        "chosen": chosen.label(),
        "config": candidate_config(chosen, stage),
        "kernels": kernel_geometries(shape),
        "analytic": {
            "ladder": scores,
            "hardware": {k: hw[k] for k in ("peak_flops",
                                            "ici_bandwidth",
                                            "hbm_limit")},
        },
        "probed": bool(can_probe and len(rungs) > 1),
    }
    plan = Plan(payload)
    if save:
        plan.save(cache_dir=cache_dir)
    return plan
