"""`ds_plan` — emit, inspect, and refresh persisted schedule plans.

Mirrors `ds_lint`/`ds_report`: zero-argument friendly, `--json` for
machine consumers. Default run is ANALYTIC-ONLY (no device work, safe
on a backendless host); `--probe` opts into the measured ladder, which
builds a real engine per surviving rung and times actual train steps —
subject to the same degrades as the kernel autotuners (multi-host
deterministic, interpret-mode and `DS_TPU_AUTOTUNE=0` analytic-only).
"""

import argparse
import json
import sys

from .cost_model import ModelShape
from .plan import latest_plan, plan_cache_dir
from .search import build_plan, candidate_config

# Named model geometries (the bench ladder's shapes); batch_per_chip
# matches the headline rows' defaults.
PRESETS = {
    "125m": dict(num_layers=12, hidden_size=768, num_heads=12,
                 seq_len=1024, vocab_size=50304, batch_per_chip=48),
    "1.3b": dict(num_layers=24, hidden_size=2048, num_heads=16,
                 seq_len=1024, vocab_size=50304, batch_per_chip=16),
    "gpt2xl": dict(num_layers=48, hidden_size=1600, num_heads=25,
                   seq_len=1024, vocab_size=50304, batch_per_chip=8),
}


def _shape_from_args(args):
    if args.preset:
        base = dict(PRESETS[args.preset])
    else:
        base = {}
    for field, flag in (("num_layers", args.layers),
                        ("hidden_size", args.hidden),
                        ("num_heads", args.heads),
                        ("seq_len", args.seq),
                        ("vocab_size", args.vocab),
                        ("batch_per_chip", args.batch_per_chip)):
        if flag is not None:
            base[field] = int(flag)
    missing = [f for f in ("num_layers", "hidden_size", "num_heads",
                           "seq_len", "vocab_size", "batch_per_chip")
               if f not in base]
    if missing:
        raise SystemExit(
            f"ds_plan: missing model shape fields {missing}; pass "
            f"--preset {{{','.join(sorted(PRESETS))}}} or the explicit "
            f"flags")
    return ModelShape(**base)


def _make_probe(shape, stage):
    """candidate -> blockable: one real train step on the candidate's
    resolved config (the Autotuner times it outside traced code; its
    warmup call absorbs the XLA compile)."""
    import numpy as np

    import jax

    import deeperspeed_tpu
    from ..models.gpt_neox import GPTNeoX, GPTNeoXConfig

    cfg = GPTNeoXConfig(vocab_size=shape.vocab_size,
                        hidden_size=shape.hidden_size,
                        num_layers=shape.num_layers,
                        num_heads=shape.num_heads,
                        max_seq_len=shape.seq_len)
    model = GPTNeoX(cfg, use_pallas=True)
    params = model.init_params(jax.random.PRNGKey(0))
    n_chips = len(jax.devices())
    batch = shape.batch_per_chip * n_chips
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          size=(1, batch, shape.seq_len), dtype=np.int32)
    engines = {}

    def probe(cand):
        eng = engines.get(cand)
        if eng is None:
            config_params = {
                "train_batch_size": batch,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 10_000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "fp16": {"enabled": True, "type": "bfloat16"},
            }
            config_params.update(candidate_config(cand, stage))
            eng, *_ = deeperspeed_tpu.initialize(
                model=model, model_parameters=params,
                config_params=config_params)
            engines[cand] = eng
        return eng.train_batch(batch=(tokens, tokens))

    return probe


def _print_plan(plan, out=sys.stdout):
    p = plan.payload
    print("-" * 64, file=out)
    print("DeeperSpeed-TPU schedule plan", file=out)
    print("-" * 64, file=out)
    rows = [("fingerprint", p["fingerprint"]),
            ("device kind", p["device_kind"]),
            ("model shape", p["shape_key"]),
            ("world", p["world"]),
            ("chosen", p["chosen"]),
            ("probed", p["probed"])]
    sched = p["config"]["zero_optimization"]["schedule"]
    rows += [(f"schedule.{k}", v) for k, v in sorted(sched.items())]
    rows += [("activation ckpt",
              p["config"]["activation_checkpointing"]["policy"]),
             ("kernels", {k: v for k, v in p["kernels"].items()
                          if v is not None} or "none resolved")]
    for name, value in rows:
        print(f"{name:.<24} {value}", file=out)
    ladder = p["analytic"]["ladder"]
    print("analytic ladder (fastest first):", file=out)
    for label, s in sorted(ladder.items(),
                           key=lambda kv: kv[1]["step_s"]):
        print(f"  {label:<28} step {s['step_s'] * 1e3:8.2f} ms  "
              f"(compute {s['compute_s'] * 1e3:.2f}, collective "
              f"{s['collective_s'] * 1e3:.2f}, mem "
              f"{s['memory_bytes'] / (1 << 30):.2f} GiB)", file=out)
    print("-" * 64, file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_plan",
        description="profile-guided schedule planner (docs/planner.md)")
    ap.add_argument("--preset", choices=sorted(PRESETS))
    ap.add_argument("--layers", type=int)
    ap.add_argument("--hidden", type=int)
    ap.add_argument("--heads", type=int)
    ap.add_argument("--seq", type=int)
    ap.add_argument("--vocab", type=int)
    ap.add_argument("--batch-per-chip", type=int)
    ap.add_argument("--stage", type=int, default=3)
    ap.add_argument("--top-k", type=int, default=None,
                    help="analytic survivors to probe (default 4)")
    ap.add_argument("--probe", action="store_true",
                    help="measure the surviving rungs on real steps "
                         "(requires DS_TPU_AUTOTUNE=1 and a real "
                         "accelerator; degrades to analytic-only)")
    ap.add_argument("--quant", action="store_true",
                    help="let the plan consider quantized-FFN recipes "
                         "(changes training numerics; default off)")
    ap.add_argument("--no-offload", action="store_true",
                    help="exclude offload tiers from the search")
    ap.add_argument("--force", action="store_true",
                    help="replan even when a cached plan exists")
    ap.add_argument("--cache-dir", default=None,
                    help=f"plan cache (default {plan_cache_dir()})")
    ap.add_argument("--out", default=None,
                    help="also write the plan JSON to this path")
    ap.add_argument("--show", action="store_true",
                    help="print the newest cached plan and exit")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.show:
        plan = latest_plan(args.cache_dir)
        if plan is None:
            print("ds_plan: no cached plans", file=sys.stderr)
            return 1
    else:
        shape = _shape_from_args(args)
        probe = None
        if args.probe:
            probe = _make_probe(shape, args.stage)
        kwargs = dict(stage=args.stage, probe=probe,
                      cache_dir=args.cache_dir, force=args.force,
                      allow_quant=args.quant,
                      allow_offload=not args.no_offload)
        if args.top_k is not None:
            kwargs["top_k"] = args.top_k
        plan = build_plan(shape, **kwargs)
        if args.out:
            plan.save(path=args.out)

    if args.json:
        print(json.dumps(plan.payload, indent=2, sort_keys=True,
                         default=str))
    else:
        _print_plan(plan)
    return 0


if __name__ == "__main__":
    sys.exit(main())
