"""RLDriver: the co-located train+serve online-RL loop (docs/rl.md).

One process holds both engines: the `DeepSpeedEngine` whose `loss_fn`
the "rl" config block swapped for PPO-clip/DPO, and an
`InferenceEngine` generating rollouts under the continuous-batching
scheduler from the SAME initial weights. Each iteration:

    rollout (serve) -> reward -> reference/behavior logprobs ->
    train_batch (one update) -> hot_swap_weights (train->serve)

The loop is deterministic and replayable: rollout sampling is a pure
function of (inference.seed, sampler step counter), the training side
of (PR 3 full-state resume: micro_steps drive the train rng), and
checkpoints COMMIT only at iteration boundaries with the driver state
(iteration counter, prompt cursor, sampler keys, buffer counters) in
`client_state` — so a SIGTERM/`os._exit` mid-iteration resumes from the
last committed boundary and replays the killed iteration bit-exactly.
"""

import os

import jax
import numpy as np

from ..inference.engine import InferenceEngine
from ..runtime import constants as c
from ..runtime.config import DeepSpeedConfigError
from ..utils.logging import logger
from .buffer import RolloutBuffer
from .losses import token_logprobs

# the frozen-reference snapshot rides NEXT TO the engine checkpoints:
# written exactly once (iteration 0), loaded on resume — re-snapshotting
# the CURRENT (trained) params as "reference" would silently zero the KL
# anchor every restart
REF_SNAPSHOT = "rl_ref_params.pt"


def _round_up8(n):
    return -(-n // 8) * 8


class RLDriver:
    """Drives the online-RL loop over a training engine built with an
    enabled "rl" config block.

    ``prompts`` is a list of token-id lists, cycled deterministically;
    ``reward_fn(prompt_tokens, response_tokens) -> float`` scores each
    engine-generated rollout; ``serve_config`` is the co-resident
    serving engine's config (a dict with an "inference" block, or a
    DeepSpeedConfig) — its ``seed`` is the rollout sampling seed.
    """

    def __init__(self, engine, prompts, reward_fn, serve_config,
                 draft_model=None, draft_params=None, checkpoint_dir=None,
                 eos_token_id=None):
        p = getattr(engine._config, "rl_params", None)
        if not p:
            raise DeepSpeedConfigError(
                "RLDriver needs an engine built with an enabled \"rl\" "
                "config block (it installs the RL loss_fn at engine "
                "init; there is no post-hoc swap)")
        if engine.gradient_accumulation_steps() != 1:
            raise DeepSpeedConfigError(
                "the RL driver updates on exactly one rollout batch per "
                "iteration: set gradient_accumulation_steps to 1")
        if not prompts:
            raise DeepSpeedConfigError("RLDriver needs at least one prompt")
        prompts = [list(map(int, pr)) for pr in prompts]
        if any(not pr for pr in prompts):
            raise DeepSpeedConfigError("RLDriver prompts must be non-empty")

        self.engine = engine
        self.rl_params = p
        self.prompts = prompts
        self.reward_fn = reward_fn
        self.checkpoint_dir = checkpoint_dir
        self.eos_token_id = eos_token_id
        self.loss_name = p[c.RL_LOSS]
        self.group_size = p[c.RL_GROUP_SIZE]
        self.rollouts_per_iteration = p[c.RL_ROLLOUTS_PER_ITERATION]
        self.max_new_tokens = p[c.RL_MAX_NEW_TOKENS]
        self.group_count = self.rollouts_per_iteration // self.group_size
        self.checkpoint_interval = p[c.RL_CHECKPOINT_INTERVAL]

        # ONE compiled train/eval shape for the whole run
        longest = max(len(pr) for pr in prompts)
        seq_len = p[c.RL_SEQUENCE_LENGTH] or _round_up8(
            longest + self.max_new_tokens)
        if longest + self.max_new_tokens > seq_len:
            raise DeepSpeedConfigError(
                f"rl.{c.RL_SEQUENCE_LENGTH} {seq_len} cannot hold the "
                f"longest prompt ({longest}) + {c.RL_MAX_NEW_TOKENS} "
                f"({self.max_new_tokens})")
        model = engine.module_obj
        if seq_len > model.config.max_seq_len:
            raise DeepSpeedConfigError(
                f"rl sequence_length {seq_len} exceeds the model's "
                f"max_seq_len {model.config.max_seq_len}")
        self.sequence_length = seq_len

        # the update batch the engine was configured for must match the
        # rollout geometry EXACTLY — a mismatch is a recompile per
        # iteration at best, a silent wrong-batch at worst
        update_rows = (self.rollouts_per_iteration
                       if self.loss_name == "ppo_clip"
                       else 2 * self.group_count)
        if engine.train_batch_size() != update_rows:
            raise DeepSpeedConfigError(
                f"train_batch_size {engine.train_batch_size()} != the RL "
                f"update batch {update_rows} rows ("
                f"{'rollouts_per_iteration' if self.loss_name == 'ppo_clip' else 'one chosen/rejected pair per prompt group'}"
                f"): align the batch triad with the rl block")

        # -- frozen reference ------------------------------------------------
        ref = None
        if checkpoint_dir is not None:
            ref_path = os.path.join(checkpoint_dir, REF_SNAPSHOT)
            if os.path.exists(ref_path):
                from ..checkpoint.serialization import load_obj
                ref = load_obj(ref_path)
                logger.info(f"rl: loaded frozen reference from {ref_path}")
        if ref is None:
            ref = jax.tree_util.tree_map(
                np.asarray, engine.params_to_natural(engine.state.params))
            if checkpoint_dir is not None:
                from ..checkpoint.serialization import save_obj
                os.makedirs(checkpoint_dir, exist_ok=True)
                save_obj(ref, os.path.join(checkpoint_dir, REF_SNAPSHOT))

        self.buffer = RolloutBuffer(model, ref, p, seq_len)

        # -- co-resident serving engine (BORROWED monitor: Train/* and
        #    Serve/* scalars interleave into one event stream without the
        #    serve drain closing it under the training engine) -------------
        self.serve = InferenceEngine(
            model, config=serve_config,
            params=engine.params_to_natural(engine.state.params),
            monitor=engine.monitor, owns_monitor=False,
            draft_model=draft_model, draft_params=draft_params)

        self.iteration = 0
        self.cursor = 0
        self.last_iteration_stats = None
        self.stats = {"iterations": 0, "rollout_tokens": 0,
                      "rollout_s": 0.0, "swap_ms": 0.0,
                      "compile_delta": 0}

    # -- checkpoint / resume -----------------------------------------------

    def _client_state(self):
        return {"rl": {
            "iteration": int(self.iteration),
            "cursor": int(self.cursor),
            "sampler": self.serve.sampler_state(),
            "buffer": self.buffer.state_dict(),
        }}

    def save_checkpoint(self, tag=None):
        if self.checkpoint_dir is None:
            raise DeepSpeedConfigError(
                "RLDriver was built without checkpoint_dir")
        return self.engine.save_checkpoint(
            self.checkpoint_dir, tag=tag,
            client_state=self._client_state())

    def resume(self, tag=None):
        """Restore the last committed iteration boundary: engine full
        state (params/optimizer/micro_steps -> train rng), driver
        counters, serve sampler streams — then hot-swap the restored
        weights into the serving engine so both sides resume from the
        SAME policy. Returns True when a checkpoint was found."""
        if self.checkpoint_dir is None:
            raise DeepSpeedConfigError(
                "RLDriver was built without checkpoint_dir")
        path, client = self.engine.load_checkpoint(self.checkpoint_dir,
                                                   tag=tag)
        if path is None:
            return False
        rl = (client or {}).get("rl")
        if rl is None:
            raise DeepSpeedConfigError(
                f"checkpoint {path} has no \"rl\" client_state: it was "
                f"not written by an RLDriver (a pretraining checkpoint "
                f"cannot pin the sampler streams)")
        self.iteration = int(rl["iteration"])
        self.cursor = int(rl["cursor"])
        self.serve.restore_sampler_state(rl["sampler"])
        self.buffer.load_state_dict(rl["buffer"])
        self.serve.hot_swap_weights(
            self.engine.params_to_natural(self.engine.state.params))
        logger.info(f"rl: resumed at iteration {self.iteration} "
                    f"from {path}")
        return True

    # -- the loop ------------------------------------------------------------

    def _iteration_prompts(self):
        idx = [(self.cursor + i) % len(self.prompts)
               for i in range(self.group_count)]
        return [self.prompts[i]
                for i in idx for _ in range(self.group_size)]

    def run_iteration(self):
        """One full rollout->update->swap iteration; returns its stats
        dict. Determinism contract: everything here is a pure function
        of (committed engine state, committed sampler state, prompt
        cursor) — the only checkpoint commit happens AFTER the swap, at
        the iteration boundary."""
        engine, serve = self.engine, self.serve
        batch_prompts = self._iteration_prompts()
        outputs, rstats = serve.generate_rollouts(
            batch_prompts, self.max_new_tokens,
            eos_token_id=self.eos_token_id)
        rewards = [float(self.reward_fn(pr, out))
                   for pr, out in zip(batch_prompts, outputs)]
        rollouts = [{"prompt": pr, "response": out, "reward": rw}
                    for pr, out, rw in zip(batch_prompts, outputs, rewards)]

        tokens, mask = self.buffer.pad(rollouts)
        ref_logp = self.buffer.ref_logprobs(tokens)
        mean_kl = 0.0
        if self.loss_name == "ppo_clip":
            # behavior policy = the weights that SAMPLED this batch
            # (pre-update), teacher-forced through the engine's fused
            # eval path — fixed [N, S] shape, one compile at warmup
            _, logits = engine.eval_batch(tokens, return_logits=True)
            behavior = np.asarray(token_logprobs(logits, tokens))
            denom = max(float(mask.sum()), 1.0)
            mean_kl = float(((behavior - ref_logp) * mask).sum() / denom)
            batch = self.buffer.build_ppo_batch(tokens, mask, behavior,
                                                ref_logp, rewards)
        else:
            batch = self.buffer.build_dpo_batch(tokens, mask, ref_logp,
                                                rewards)

        # gas == 1: one pre-stacked [1, rows, ...] micro-batch
        stacked = jax.tree_util.tree_map(lambda x: x[None], batch)
        loss = float(engine.train_batch(batch=stacked))

        swap = serve.hot_swap_weights(
            engine.params_to_natural(engine.state.params))

        self.iteration += 1
        self.cursor = (self.cursor + self.group_count) % len(self.prompts)

        out = {
            "iteration": self.iteration,
            "loss": loss,
            "mean_reward": float(np.mean(rewards)),
            "mean_kl": mean_kl,
            "rollout_tokens": rstats["rollout_tokens"],
            "rollout_tokens_per_s": rstats["tokens_per_s"],
            "rollout_s": rstats["rollout_s"],
            "swap_ms": swap["swap_ms"],
            # compile growth this iteration (rollout + swap); 0 after
            # the warmup iteration is the zero-recompile pin
            "compile_delta": rstats["compile_delta"]
            + swap["compile_delta"],
        }
        if "spec_acceptance_rate" in rstats:
            out["spec_acceptance_rate"] = rstats["spec_acceptance_rate"]
        self.last_iteration_stats = out
        self.stats["iterations"] += 1
        self.stats["rollout_tokens"] += out["rollout_tokens"]
        self.stats["rollout_s"] += out["rollout_s"]
        self.stats["swap_ms"] += out["swap_ms"]
        self.stats["compile_delta"] += out["compile_delta"]

        if engine.monitor is not None:
            engine.monitor.record(engine.global_samples, {
                f"Train/RL/{k}": float(v) for k, v in out.items()})

        if self.checkpoint_dir is not None and \
                self.iteration % self.checkpoint_interval == 0:
            self.save_checkpoint()
        return out

    def train(self, num_iterations):
        """Run `num_iterations` iterations; returns the per-iteration
        stats list."""
        return [self.run_iteration() for _ in range(num_iterations)]
