"""Pluggable RL loss registry (docs/rl.md).

Each entry is a BUILDER ``build(model, rl_params) -> loss_fn`` where the
returned ``loss_fn(params, batch, rng=None)`` has the exact signature
`DeepSpeedEngine` expects of ``self.loss_fn``: it rides
``jax.value_and_grad`` under every GSPMD ZeRO stage and the host-offload
optimizer unchanged. ``batch`` is a dict pytree — ``_shard_batch`` /
``train_batch``'s micro-batch stacking are tree_maps, so dict batches
flow through the engine with no special-casing.

Both losses consume TEACHER-FORCED token logprobs: one full forward over
the padded rollout ``tokens [B, S]``, ``log_softmax`` over positions
``[:, :-1]`` gathered at the next token ``tokens[:, 1:]`` -> ``[B, S-1]``,
with a ``mask [B, S-1]`` selecting the response (generated) transitions.
Prompt and pad positions carry zero weight, so the pad id is
loss-irrelevant by construction.
"""

import jax
import jax.numpy as jnp

from ..runtime import constants as c
from ..runtime.config import DeepSpeedConfigError

_RL_LOSSES = {}


def register_rl_loss(name):
    """Decorator: register ``build(model, rl_params) -> loss_fn`` under
    ``name`` (the value of the ``rl.loss`` config key)."""

    def wrap(build):
        _RL_LOSSES[name] = build
        return build

    return wrap


def get_rl_loss(name):
    """Look up a registered RL loss builder by ``rl.loss`` name."""
    try:
        return _RL_LOSSES[name]
    except KeyError:
        raise DeepSpeedConfigError(
            f"Unknown RL loss {name!r}; registered: "
            f"{sorted(_RL_LOSSES)}") from None


def token_logprobs(logits, tokens):
    """Next-token logprobs: ``[B, S, V]`` logits + ``[B, S]`` tokens ->
    ``[B, S-1]`` logprob of ``tokens[:, j]`` under position ``j-1``.

    log_softmax runs in fp32: PPO ratios exponentiate a logprob
    DIFFERENCE, and bf16 rounding there is a spurious off-policy
    signal, not noise.
    """
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    target = tokens[:, 1:].astype(jnp.int32)
    return jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]


def _masked_mean(x, mask):
    return (x * mask).sum() / jnp.maximum(mask.sum(), 1.0)


@register_rl_loss("ppo_clip")
def build_ppo_clip(model, rl_params):
    """PPO-clip with a k1 KL penalty against the frozen reference.

    batch: tokens [B,S] i32, mask [B,S-1] f32, behavior_logp [B,S-1],
    ref_logp [B,S-1], advantages [B]. ``behavior_logp`` is the policy
    that SAMPLED the rollout (pre-update weights), recomputed
    teacher-forced through ``eval_batch`` so sampler-side dtype/kernel
    choices cannot skew the ratio.
    """
    clip_ratio = rl_params[c.RL_CLIP_RATIO]
    kl_coef = rl_params[c.RL_KL_COEF]

    def loss_fn(params, batch, rng=None):
        del rng  # sampling happened serve-side; the update is deterministic
        logits = model.apply(params, batch["tokens"])
        logp = token_logprobs(logits, batch["tokens"])
        mask = batch["mask"].astype(jnp.float32)
        ratio = jnp.exp(logp - batch["behavior_logp"])
        adv = batch["advantages"][:, None]
        clipped = jnp.clip(ratio, 1.0 - clip_ratio, 1.0 + clip_ratio)
        pg = -_masked_mean(jnp.minimum(ratio * adv, clipped * adv), mask)
        kl = _masked_mean(logp - batch["ref_logp"], mask)
        return pg + kl_coef * kl

    return loss_fn


@register_rl_loss("dpo")
def build_dpo(model, rl_params):
    """DPO over chosen/rejected pairs (2305.18290).

    batch: tokens [2P,S] with chosen rollouts at even rows and their
    rejected partners at the following odd rows, mask [2P,S-1],
    ref_logp [2P,S-1]. Sequence logprob = masked token-logprob sum;
    loss = -mean log sigmoid(beta * (margin_chosen - margin_rejected))
    where margin = policy seq-logprob minus frozen-reference seq-logprob.
    """
    beta = rl_params[c.RL_BETA]

    def loss_fn(params, batch, rng=None):
        del rng
        logits = model.apply(params, batch["tokens"])
        logp = token_logprobs(logits, batch["tokens"])
        mask = batch["mask"].astype(jnp.float32)
        seq_logp = (logp * mask).sum(axis=-1)
        ref_seq_logp = (batch["ref_logp"] * mask).sum(axis=-1)
        margin = seq_logp - ref_seq_logp
        pref = margin[0::2] - margin[1::2]
        return -jnp.mean(jax.nn.log_sigmoid(beta * pref))

    return loss_fn
