"""Online-RL driver: co-located train+serve loop (docs/rl.md).

The training engine (`DeepSpeedEngine`) and the serving engine
(`InferenceEngine`) live in ONE process: rollouts are generated under
the continuous-batching scheduler, PPO-clip/DPO losses train on the
existing engine substrate through the `loss_fn` registry hook, and
updated weights flow train->serve by in-process hot-swap with zero
recompiles (params are runtime jit args on both sides).
"""

from .losses import get_rl_loss, register_rl_loss, token_logprobs
from .buffer import RolloutBuffer
from .driver import RLDriver

__all__ = [
    "RLDriver",
    "RolloutBuffer",
    "get_rl_loss",
    "register_rl_loss",
    "token_logprobs",
]
