"""RolloutBuffer: engine-generated rollouts -> fixed-shape RL batches.

The buffer owns the geometry contract that makes the loop
zero-recompile: every iteration's rollouts are padded to ONE
``[rollouts_per_iteration, sequence_length]`` shape, so the training
step, the behavior-logprob eval and the frozen-reference forward each
compile exactly once at warmup. It also holds the frozen reference
params and recomputes reference logprobs through the model's
``loss_and_logits`` single-forward path (fp32 logits; the same
fork-parity API ``eval_batch`` uses), so policy/reference logprobs are
numerically comparable by construction.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import constants as c
from ..runtime.config import DeepSpeedConfigError
from .losses import token_logprobs

# pad id for positions past the rollout; never enters the loss (the
# response mask zeroes prompt and pad transitions alike)
PAD_ID = 0


class RolloutBuffer:
    """Pads, masks and scores one iteration's rollouts at a time.

    ``rollouts`` is a list of dicts with ``prompt`` (list[int]),
    ``response`` (list[int], the generated continuation) and ``reward``
    (float), grouped contiguously: rollouts ``[g*group_size, (g+1)*
    group_size)`` share prompt ``g``.
    """

    def __init__(self, model, ref_params, rl_params, sequence_length):
        self.model = model
        self.rl_params = rl_params
        self.group_size = rl_params[c.RL_GROUP_SIZE]
        self.sequence_length = int(sequence_length)
        if self.sequence_length < 2:
            raise DeepSpeedConfigError(
                f"RolloutBuffer sequence_length must be >= 2, got "
                f"{self.sequence_length}")
        # frozen on device for the life of the run: the reference policy
        # never moves, so its forward is a pure jit over (tokens,)
        self._ref_params = jax.tree_util.tree_map(jnp.asarray, ref_params)

        def _ref_logp(params, tokens):
            # loss_and_logits returns fp32 logits from the single-forward
            # fused path — the one the training-side eval also takes
            _, logits = model.loss_and_logits(params, tokens)
            return token_logprobs(logits, tokens)

        self._ref_logp = jax.jit(_ref_logp)
        # rollouts consumed over the run; checkpointed so a resumed
        # driver reports continuous telemetry
        self.consumed = 0

    # -- geometry ----------------------------------------------------------

    def pad(self, rollouts):
        """-> (tokens [N,S] int32, mask [N,S-1] float32). ``mask[i, j]``
        weights the transition predicting ``tokens[i, j+1]``: 1 exactly
        when that token was GENERATED (not prompt, not pad)."""
        n, s = len(rollouts), self.sequence_length
        tokens = np.full((n, s), PAD_ID, dtype=np.int32)
        mask = np.zeros((n, s - 1), dtype=np.float32)
        for i, r in enumerate(rollouts):
            prompt, response = list(r["prompt"]), list(r["response"])
            total = len(prompt) + len(response)
            if total > s:
                raise DeepSpeedConfigError(
                    f"Rollout {i} is {total} tokens but rl sequence_length "
                    f"is {s}: raise rl.sequence_length (fixed shapes are "
                    f"the zero-recompile contract; there is no bucket "
                    f"ladder on the training side)")
            if not response:
                raise DeepSpeedConfigError(
                    f"Rollout {i} has an empty response: nothing to score")
            tokens[i, :total] = prompt + response
            mask[i, len(prompt) - 1:total - 1] = 1.0
        return tokens, mask

    # -- scoring -----------------------------------------------------------

    def ref_logprobs(self, tokens):
        """Teacher-forced logprobs [N,S-1] under the frozen reference."""
        return np.asarray(self._ref_logp(self._ref_params, tokens))

    def advantages(self, rewards):
        """Group-normalized advantages [N] (GRPO-style): each rollout's
        reward centered/scaled within its prompt group; with group_size
        1 the whole iteration is the baseline group."""
        r = np.asarray(rewards, dtype=np.float32)
        g = self.group_size if self.group_size > 1 else len(r)
        grouped = r.reshape(-1, g)
        mean = grouped.mean(axis=1, keepdims=True)
        std = grouped.std(axis=1, keepdims=True)
        return ((grouped - mean) / (std + 1e-6)).reshape(-1)

    # -- batch assembly ----------------------------------------------------

    def build_ppo_batch(self, tokens, mask, behavior_logp, ref_logp,
                        rewards):
        self.consumed += len(tokens)
        return {
            "tokens": tokens,
            "mask": mask,
            "behavior_logp": np.asarray(behavior_logp, dtype=np.float32),
            "ref_logp": np.asarray(ref_logp, dtype=np.float32),
            "advantages": self.advantages(rewards),
        }

    def build_dpo_batch(self, tokens, mask, ref_logp, rewards):
        """Pick the (argmax, argmin)-reward pair inside each prompt
        group and interleave them chosen-first: rows [2P, S] with chosen
        at ::2, rejected at 1::2 (the layout `build_dpo` slices).
        Deterministic ties: numpy arg* take the first index."""
        self.consumed += len(tokens)
        r = np.asarray(rewards, dtype=np.float32).reshape(
            -1, self.group_size)
        groups = np.arange(r.shape[0]) * self.group_size
        chosen = groups + r.argmax(axis=1)
        rejected = groups + r.argmin(axis=1)
        order = np.stack([chosen, rejected], axis=1).reshape(-1)
        return {
            "tokens": tokens[order],
            "mask": mask[order],
            "ref_logp": np.asarray(ref_logp, dtype=np.float32)[order],
        }

    # -- resume ------------------------------------------------------------

    def state_dict(self):
        return {"consumed": int(self.consumed)}

    def load_state_dict(self, state):
        self.consumed = int(state["consumed"])
