"""Fleet observability: cross-host aggregation + collective-skew probe.

PR 6's telemetry and PR 9's heartbeats are strictly per-host: every
process writes its own span captures and ``Train/*`` scalars, and the
heartbeat monitor's ``slow`` state is a qualitative staleness guess.
This module gives the JOB a view:

- **Cross-host aggregation** (`FleetAggregator`): every host accumulates
  its per-step wall time / data-wait / checkpoint-stall locally and, at
  the close of each ``window_steps`` window, publishes ONE bounded
  summary through the same coordination-service KV transport the
  heartbeats ride (`elasticity.heartbeat.CoordinationTransport`; the
  in-memory transport single-host). Rank 0 collects the summaries and
  emits job-level ``Train/Fleet/*`` scalars — min/median/max/skew of
  step time, data wait, and checkpoint stall across hosts, with the
  slowest host NAMED (scalar + log line).
- **Collective-skew straggler diagnosis**: every K steps
  (``skew_interval_steps``) the hosts run a cheap two-phase probe: a
  rendezvous all-gather is entered at the step boundary, each host
  measures how long IT waited for the others (waits are *durations*, so
  no cross-host clock comparison — the heartbeat module's rule), and a
  second all-gather of those waits yields per-host arrival lateness:
  the straggler is the host everyone else waited for. The instantaneous
  spread is emitted as ``Train/Fleet/step_skew_ms`` with a persistent
  per-host EMA, and the quantitative verdict feeds
  `PeerHealthMonitor.note_skew` — the heartbeat ``slow`` escalation and
  the hang watchdog's LOCAL-vs-peer verdict can then cite "host 3 is
  180ms/step behind the median for 50 consecutive steps" instead of a
  staleness guess. Single-host (tests, the fault-injection harness) the
  probe reads the heartbeat monitor's SIMULATED peers: a ``slow_peer``
  fault's delay becomes that host's arrival lateness, so detection is
  drivable on one box.
- **Merged Perfetto export**: when a telemetry capture window closes,
  each host ships its (bounded) span events + environment fingerprint
  (`env_report.env_fingerprint`) + kernel dispatch report
  (`ops.dispatch_report`) through the trace transport; rank 0 merges
  them into ONE Chrome-trace JSON with one lane ("pid") per host —
  process_name metadata names the lanes — loadable in Perfetto.

Zero-overhead discipline: the aggregator exists only when the validated
``telemetry.fleet`` block enables it; per-step cost is a few float
appends, the skew probe's collective is amortized over K steps, and
nothing here ever blocks on the KV store inside a step (publishes are
small, reads happen only on rank 0 at window close).
"""

import json
import os
import statistics
import time

from ..utils.logging import log_dist, logger

FLEET_SUMMARY_PREFIX = "ds_fleet/sum"
FLEET_TRACE_PREFIX = "ds_fleet/trace"

# the Train/Fleet cross-host families emitted at window close
FLEET_WINDOW_METRICS = ("step_time_ms", "data_wait_ms", "ckpt_stall_ms")


def _default_transports():
    """(summary, trace) transports: coordination-service KV when a
    multi-host client exists, process-local otherwise. Wrapped in the
    shared retry policy (`utils/kv_retry.py`): transient KV blips are
    retried with capped backoff × jitter, and persistent failure
    degrades to a local in-memory store with ONE warning — fleet
    scalars then cover this host only instead of erroring every
    window (the aggregator's `_note_transport_error` stays as the
    last-resort backstop for transports injected by tests)."""
    import jax

    from ..elasticity.heartbeat import (CoordinationTransport,
                                        InMemoryTransport)
    from ..utils.kv_retry import wrap_kv_transport
    if jax.process_count() > 1:
        from ..utils.distributed import _distributed_client
        client = _distributed_client()
        if client is not None:
            return (wrap_kv_transport(
                        CoordinationTransport(
                            client, prefix=FLEET_SUMMARY_PREFIX),
                        degrade_to_local=True, name="fleet summary"),
                    wrap_kv_transport(
                        CoordinationTransport(
                            client, prefix=FLEET_TRACE_PREFIX),
                        degrade_to_local=True, name="fleet trace"))
        logger.warning(  # pragma: no cover - private-API drift
            "fleet: no coordination client available; cross-host "
            "aggregation degrades to process-local summaries")
    return InMemoryTransport(), InMemoryTransport()


class FleetAggregator:
    """Per-host accumulator + rank-0 collector (module docstring).

    ``params`` is the validated ``telemetry.fleet`` dict
    (`DeepSpeedConfig._parse_telemetry_block`). Tests drive multiple
    simulated hosts by constructing several aggregators with explicit
    ``process_index`` over SHARED in-memory transports, and inject a
    fake ``gather`` to script the skew probe."""

    def __init__(self, params, process_index=None, process_count=None,
                 summary_transport=None, trace_transport=None,
                 gather=None, clock=time.perf_counter):
        import jax
        self.window_steps = int(params.get("window_steps", 50))
        self.skew_interval = int(params.get("skew_interval_steps", 10))
        self.ema_beta = float(params.get("skew_ema_beta", 0.9))
        self.threshold_ms = float(params.get("skew_slow_threshold_ms",
                                             50.0))
        self.max_trace_events = int(params.get("max_trace_events", 2000))

        self.process_index = (jax.process_index() if process_index is None
                              else int(process_index))
        self.process_count = (jax.process_count() if process_count is None
                              else int(process_count))
        self.host = str(self.process_index)
        self.is_collector = self.process_index == 0
        if summary_transport is None and trace_transport is None:
            summary_transport, trace_transport = _default_transports()
        self.summary_transport = summary_transport
        self.trace_transport = trace_transport
        self._gather = gather
        self._clock = clock
        self._peer_monitor = None

        # window accumulators (reset at each close)
        self._w_step_s = []
        self._w_data_wait_s = 0.0
        self._w_ckpt_stall_s = 0.0
        self._steps = 0
        self._last_probe_step = 0
        self._last_window_step = 0
        self._serial = 0
        self._transport_errors = 0
        self._warned_transport = False

        # skew state: persistent per-host EMA of lateness-behind-median,
        # and the consecutive-step count each host has spent past the
        # threshold (what the escalation log cites)
        self.skew_ema_ms = {}
        self.behind_steps = {}
        self.last_skew_ms = None
        self.last_slowest = None
        self._named = set()          # hosts already log-named this episode

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------

    def bind_peer_monitor(self, monitor):
        """Attach the heartbeat monitor: the skew probe feeds it
        quantitative per-host verdicts (`note_skew`), and the simulated
        single-host gather reads its `slow_peer` fault state."""
        self._peer_monitor = monitor
        return self

    # ------------------------------------------------------------------
    # per-step hook (called from Telemetry.on_step_end)
    # ------------------------------------------------------------------

    def on_step_end(self, dt_s, data_wait_s=0.0, ckpt_stall_s=0.0,
                    steps=1):
        """Accumulate one step window; returns the Train/Fleet scalars
        due THIS step (skew at probe boundaries, cross-host stats at
        window close on the collector; {} otherwise)."""
        steps = max(int(steps), 1)
        self._w_step_s.append(float(dt_s) / steps)
        self._w_data_wait_s += float(data_wait_s)
        self._w_ckpt_stall_s += float(ckpt_stall_s)
        self._steps += steps
        scalars = {}
        if self.skew_interval > 0 and \
                self._steps - self._last_probe_step >= self.skew_interval:
            self._last_probe_step = self._steps
            scalars.update(self.probe_skew())
        if self.window_steps > 0 and \
                self._steps - self._last_window_step >= self.window_steps:
            self._last_window_step = self._steps
            scalars.update(self._close_window())
        return scalars

    # ------------------------------------------------------------------
    # collective-skew probe
    # ------------------------------------------------------------------

    def _gather_lateness_ms(self):
        """{host: arrival lateness in ms} for this probe — 0 for the
        host that reached the dispatch boundary last (everyone waited
        for it ⇒ it waited least… inverted: lateness = how much LATER
        than the earliest arrival). Three sources, in priority order:
        an injected test gather, the real two-phase all-gather
        (multi-host), or the heartbeat monitor's simulated peers."""
        if self._gather is not None:
            return dict(self._gather())
        if self.process_count > 1:
            return self._gather_real()
        return self._gather_simulated()

    def _gather_real(self):  # pragma: no cover - needs a real pod
        import numpy as np

        from jax.experimental import multihost_utils
        # phase 1: rendezvous; each host measures how long it waited
        # for the others. Waits are local DURATIONS — comparable across
        # hosts without any clock synchronization.
        t0 = self._clock()
        multihost_utils.process_allgather(np.zeros((), np.float32))
        wait_ms = (self._clock() - t0) * 1e3
        # phase 2: exchange the waits; the host that waited longest
        # arrived first, so lateness_i = max(waits) - wait_i
        waits = np.asarray(
            multihost_utils.process_allgather(np.float32(wait_ms)),
            dtype=np.float64).reshape(-1)
        lateness = waits.max() - waits
        return {str(i): float(lateness[i]) for i in range(len(waits))}

    def _gather_simulated(self):
        """Single-host: derive lateness from the heartbeat monitor's
        simulated peers — a `slow_peer` fault's delay IS that host's
        per-step lateness, so the detect → name → escalate loop is
        drivable (and testable) on one box."""
        lateness = {self.host: 0.0}
        monitor = self._peer_monitor
        if monitor is not None:
            delays = getattr(monitor, "simulated_delays", None)
            if delays is not None:
                for name, delay_s in delays().items():
                    lateness[str(name)] = float(delay_s) * 1e3
        return lateness

    def probe_skew(self):
        """One probe: gather per-host arrival lateness, update the
        per-host EMAs and consecutive-behind counters, feed the
        heartbeat monitor, and return the Train/Fleet skew scalars."""
        try:
            lateness = self._gather_lateness_ms()
        except Exception as e:  # noqa: BLE001 - probe must not kill a step
            self._note_transport_error("skew gather", e)
            return {}
        if not lateness:
            return {}
        values = list(lateness.values())
        med = statistics.median(values)
        skew = max(values) - min(values)
        self.last_skew_ms = skew
        slowest = max(lateness, key=lateness.get)
        behind_now = {}
        for host, late in lateness.items():
            behind = late - med
            ema = self.skew_ema_ms.get(host)
            self.skew_ema_ms[host] = (behind if ema is None else
                                      self.ema_beta * ema +
                                      (1.0 - self.ema_beta) * behind)
            if behind > self.threshold_ms:
                self.behind_steps[host] = \
                    self.behind_steps.get(host, 0) + self.skew_interval
            else:
                self.behind_steps[host] = 0
                self._named.discard(host)
            behind_now[host] = behind
        self.last_slowest = slowest if skew > self.threshold_ms else None
        if self.last_slowest and self.last_slowest not in self._named:
            self._named.add(self.last_slowest)
            logger.warning(
                f"fleet skew probe: host {self.last_slowest} is "
                f"{behind_now[self.last_slowest]:.0f}ms/step behind the "
                f"median across {len(lateness)} host(s) "
                f"(skew {skew:.0f}ms)")
        monitor = self._peer_monitor
        if monitor is not None and hasattr(monitor, "note_skew"):
            monitor.note_skew(
                {h: self.skew_ema_ms[h] for h in lateness},
                dict(self.behind_steps))
        scalars = {"Train/Fleet/step_skew_ms": skew,
                   "Train/Fleet/step_skew_ema_ms":
                       max(self.skew_ema_ms.values(), default=0.0)}
        # the gauge is ALWAYS emitted (-1 = nobody past the threshold):
        # a latest-value scrape backend would otherwise keep naming the
        # last straggler forever after it recovered
        if self.last_slowest is None:
            scalars["Train/Fleet/slowest_host"] = -1.0
        else:
            try:
                scalars["Train/Fleet/slowest_host"] = \
                    float(int(self.last_slowest))
            except (TypeError, ValueError):
                pass   # non-numeric (simulated) host: leave unset
        return scalars

    # ------------------------------------------------------------------
    # window summaries (cross-host scalar aggregation)
    # ------------------------------------------------------------------

    def _note_transport_error(self, what, exc):
        self._transport_errors += 1
        if not self._warned_transport:
            self._warned_transport = True
            logger.warning(
                f"fleet: {what} failed ({type(exc).__name__}: {exc}); "
                f"fleet scalars degrade to this host only (warned once)")

    def _own_summary(self):
        n = max(len(self._w_step_s), 1)
        return {
            "serial": self._serial,
            "host": self.host,
            "steps": len(self._w_step_s),
            "step_time_ms": 1e3 * (sum(self._w_step_s) / n),
            "data_wait_ms": 1e3 * self._w_data_wait_s / n,
            "ckpt_stall_ms": 1e3 * self._w_ckpt_stall_s / n,
        }

    def _close_window(self):
        """Publish this host's window summary; on the collector, read
        every host's latest summary and emit the cross-host scalars."""
        self._serial += 1
        summary = self._own_summary()
        self._w_step_s = []
        self._w_data_wait_s = 0.0
        self._w_ckpt_stall_s = 0.0
        try:
            self.summary_transport.publish(self.host, summary)
        except Exception as e:  # noqa: BLE001
            self._note_transport_error("summary publish", e)
        if not self.is_collector:
            return {}
        try:
            summaries = self.summary_transport.read_all()
        except Exception as e:  # noqa: BLE001
            self._note_transport_error("summary collect", e)
            summaries = {}
        summaries[self.host] = summary     # own window is always current
        return self._fleet_scalars(summaries)

    def _fleet_scalars(self, summaries):
        hosts = sorted(summaries)
        scalars = {"Train/Fleet/hosts": float(len(hosts))}
        for metric in FLEET_WINDOW_METRICS:
            values = [float(summaries[h].get(metric, 0.0)) for h in hosts]
            scalars[f"Train/Fleet/{metric}_min"] = min(values)
            scalars[f"Train/Fleet/{metric}_median"] = \
                statistics.median(values)
            scalars[f"Train/Fleet/{metric}_max"] = max(values)
            scalars[f"Train/Fleet/{metric}_skew"] = \
                max(values) - min(values)
        step_times = {h: float(summaries[h].get("step_time_ms", 0.0))
                      for h in hosts}
        slowest = max(step_times, key=step_times.get)
        try:
            scalars["Train/Fleet/slowest_host_step_time"] = \
                float(int(slowest))
        except (TypeError, ValueError):
            pass
        if len(hosts) > 1 and \
                scalars["Train/Fleet/step_time_ms_skew"] > 0:
            log_dist(
                f"fleet window: {len(hosts)} hosts, step time "
                f"median {scalars['Train/Fleet/step_time_ms_median']:.1f}"
                f"ms skew {scalars['Train/Fleet/step_time_ms_skew']:.1f}"
                f"ms — slowest host {slowest} "
                f"({step_times[slowest]:.1f}ms)", ranks=[0])
        return scalars

    # ------------------------------------------------------------------
    # merged Perfetto trace (capture-window close)
    # ------------------------------------------------------------------

    def ship_capture(self, tag, events):
        """Publish this host's capture-window span events (BOUNDED —
        the coordination KV store is not a trace sink; past
        ``max_trace_events`` the tail is dropped and counted) plus the
        environment fingerprint and kernel dispatch report."""
        dropped = max(len(events) - self.max_trace_events, 0)
        events = list(events)[:self.max_trace_events]
        base = min((t0 for _, t0, _, _ in events), default=0.0)
        payload = {
            "serial": self._serial,
            "tag": str(tag),
            # host-relative microsecond timestamps: perf_counter origins
            # differ per host, so lanes align at their own window start
            "events": [[name, (t0 - base) * 1e6, dur * 1e6, depth]
                       for name, t0, dur, depth in events],
            "dropped": dropped,
            "env": _safe_env_fingerprint(),
            "dispatch": _safe_dispatch_report(),
        }
        try:
            self.trace_transport.publish(self.host, payload)
        except Exception as e:  # noqa: BLE001
            self._note_transport_error("trace publish", e)

    # how long the collector waits for peers' capture payloads before
    # merging what arrived: peers close the same scheduled window a few
    # ms apart, so rank 0 must not read-and-merge instantly (it would
    # silently drop every lane but its own on a real pod)
    merge_timeout_s = 5.0

    def merged_trace(self, tag, trace_dir, timeout_s=None):
        """Rank-0 collector: merge every host's shipped capture for
        ``tag`` into one Chrome-trace JSON — one lane (pid) per host,
        process_name metadata naming the lanes, env + dispatch reports
        embedded as trace metadata. Waits (bounded by
        ``merge_timeout_s``) until all ``process_count`` hosts have
        shipped the tag; an incomplete merge warns with the lane count.
        Returns the path (None off-rank-0 or when nothing was
        shipped)."""
        if not self.is_collector:
            return None
        timeout_s = self.merge_timeout_s if timeout_s is None \
            else float(timeout_s)
        deadline = self._clock() + timeout_s
        shipped = {}
        while True:
            try:
                current = self.trace_transport.read_all()
            except Exception as e:  # noqa: BLE001
                self._note_transport_error("trace collect", e)
                return None
            shipped = {h: p for h, p in current.items()
                       if p.get("tag") == str(tag)}
            if len(shipped) >= self.process_count or \
                    self._clock() >= deadline:
                break
            time.sleep(0.05)
        if not shipped:
            return None
        if len(shipped) < self.process_count:
            logger.warning(
                f"fleet: merged capture '{tag}' has only "
                f"{len(shipped)}/{self.process_count} host lane(s) — "
                f"peers had not published within {timeout_s:.1f}s")
        trace_events, hosts_meta = [], {}
        for host in sorted(shipped):
            payload = shipped[host]
            try:
                pid = int(host)
            except (TypeError, ValueError):
                pid = len(hosts_meta) + 1000
            trace_events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"host{host}"}})
            for name, ts_us, dur_us, depth in payload.get("events", []):
                trace_events.append({
                    "name": name, "ph": "X", "pid": pid, "tid": depth,
                    "ts": ts_us, "dur": dur_us,
                    "cat": "deeperspeed_tpu"})
            hosts_meta[str(host)] = {
                "env": payload.get("env"),
                "dispatch": payload.get("dispatch"),
                "dropped_events": payload.get("dropped", 0)}
        trace = {"traceEvents": trace_events, "displayTimeUnit": "ms",
                 "otherData": {"hosts": hosts_meta}}
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, f"fleet_spans_{tag}.json")
        with open(path, "w") as f:
            json.dump(trace, f)
        log_dist(f"fleet: merged capture '{tag}' — {len(hosts_meta)} "
                 f"host lane(s) -> {path}", ranks=[0])
        return path


def _safe_env_fingerprint():
    try:
        from ..env_report import env_fingerprint
        return env_fingerprint()
    except Exception:  # noqa: BLE001 - metadata must not break a capture
        return None


def _safe_dispatch_report():
    try:
        from ..ops import dispatch_report
        return dispatch_report()
    except Exception:  # noqa: BLE001
        return None


def build_fleet(params, **kwargs):
    """FleetAggregator (or None) from the validated ``telemetry.fleet``
    params dict."""
    if not params or not params.get("enabled"):
        return None
    return FleetAggregator(params, **kwargs)
