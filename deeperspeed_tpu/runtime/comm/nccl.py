"""`NcclBackend`-compatible compressed-allreduce backend.

Reference: `deepspeed/runtime/comm/nccl.py:14-186` — the two-phase
error-compensated 1-bit allreduce used by 1-bit Adam/LAMB:

    phase 1: each worker quantizes its (error-compensated) chunk to sign
             bits + an L1 scale, all_to_all's chunks to their "server"
             rank;
    phase 2: each server averages its chunk, re-quantizes with its own
             error feedback, and allgathers the result.

On TPU the transport is the ICI mesh and the quantized payload travels as
int8 signs + a per-chunk fp32 scale via `shard_map` collectives
(`all_to_all` + `all_gather`) — same wire volume as the reference's
cupy-packed bits to within the 8×-vs-1× sign packing, same numerics. A
host (numpy) fallback runs the identical math in one process so the
backend is testable and usable without a mesh.

The class name/API is kept for drop-in parity with user code written
against the reference (`NcclBackend(mpu).compressed_allreduce(...)`).
"""

import jax.numpy as jnp

from .compressed import compressed_allreduce_dense


class NcclBackend:
    """Error-compensated compressed allreduce over the data-parallel axis.

    Parameters mirror the reference (`nccl.py:14`): an optional
    Megatron-style ``mpu`` restricts the reduction to its data-parallel
    group; on TPU that is the mesh ``data`` axis.
    """

    def __init__(self, mpu=None, axis_name="data"):
        self.mpu = mpu
        self.axis_name = axis_name

    # -- in-mesh (shard_map / pjit) path ------------------------------

    def compressed_allreduce_in_mesh(self, x, worker_error):
        """Usable inside shard_map: returns (averaged, new_worker_error)."""
        return compressed_allreduce_dense(x, worker_error, self.axis_name)

    # -- host path (single process or explicit buffers) ---------------

    def compressed_allreduce(self, buffer_m, worker_error, server_error,
                             local_rank=None):
        """Reference-signature compressed allreduce (`nccl.py:47`).

        ``buffer_m`` is this rank's flat momentum buffer; in a
        single-process TPU program every rank's buffer lives in the same
        process, so `buffer_m` may be a list of per-rank buffers. Returns
        the updated buffer(s) and mutates nothing.
        """
        single = not isinstance(buffer_m, (list, tuple))
        buffers = [buffer_m] if single else list(buffer_m)
        errors = [worker_error] if single else list(worker_error)
        world = len(buffers)

        # phase 1: worker-side quantization with error feedback
        quantized, new_worker_errors = [], []
        for buf, err in zip(buffers, errors):
            buf = jnp.asarray(buf, jnp.float32)
            err = jnp.asarray(err, jnp.float32)
            compensated = buf + err
            scale = jnp.mean(jnp.abs(compensated))
            signs = jnp.where(compensated >= 0, 1.0, -1.0)
            q = signs * scale
            quantized.append(q)
            new_worker_errors.append(compensated - q)

        # phase 2: server-side average + re-quantization with the server
        # error buffer
        mean = sum(quantized) / world
        server_error = jnp.asarray(server_error, jnp.float32)
        compensated = mean + server_error
        scale2 = jnp.mean(jnp.abs(compensated))
        signs2 = jnp.where(compensated >= 0, 1.0, -1.0)
        out = signs2 * scale2
        new_server_error = compensated - out

        outs = [out for _ in buffers]
        if single:
            return outs[0], new_worker_errors[0], new_server_error
        return outs, new_worker_errors, new_server_error
