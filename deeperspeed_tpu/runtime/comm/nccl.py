"""`NcclBackend`-compatible compressed-allreduce backend.

Reference: `deepspeed/runtime/comm/nccl.py:14-186` — the two-phase
error-compensated 1-bit allreduce used by 1-bit Adam/LAMB:

    phase 1: each worker quantizes its (error-compensated) chunk to sign
             bits + an L1 scale, all_to_all's chunks to their "server"
             rank;
    phase 2: each server averages its chunk, re-quantizes with its own
             error feedback, and allgathers the result.

On TPU the transport is the ICI mesh and the quantized payload travels as
int8 signs + a per-chunk fp32 scale via `shard_map` collectives
(`all_to_all` + `all_gather`) — same wire volume as the reference's
cupy-packed bits to within the 8×-vs-1× sign packing, same numerics. A
host (numpy) fallback runs the identical math in one process so the
backend is testable and usable without a mesh.

The class name/API is kept for drop-in parity with user code written
against the reference (`NcclBackend(mpu).compressed_allreduce(...)`).
"""

import jax.numpy as jnp

from .compressed import compressed_allreduce_dense


class NcclBackend:
    """Error-compensated compressed allreduce over the data-parallel axis.

    Parameters mirror the reference (`nccl.py:14`): an optional
    Megatron-style ``mpu`` restricts the reduction to its data-parallel
    group; on TPU that is the mesh ``data`` axis.
    """

    def __init__(self, mpu=None, axis_name="data"):
        self.mpu = mpu
        self.axis_name = axis_name

    # -- in-mesh (shard_map / pjit) path ------------------------------

    def compressed_allreduce_in_mesh(self, x, worker_error):
        """Usable inside shard_map: returns (averaged, new_worker_error).
        Dense single-phase variant (quantization numerics only)."""
        return compressed_allreduce_dense(x, worker_error, self.axis_name)

    def compressed_allreduce_packed(self, x, worker_error, server_error,
                                    world):
        """The real wire protocol inside shard_map (reference
        `nccl.py:47-186`): packed int8 sign bits via all_to_all +
        all_gather with two-phase error feedback — ~16× less wire volume
        than an fp32 ring allreduce. `x` is this rank's flat buffer
        (length % world·8 == 0); `server_error` is the rank's phase-2
        chunk buffer [n/world]."""
        from .compressed import compressed_allreduce_two_phase
        return compressed_allreduce_two_phase(
            x, worker_error, server_error, self.axis_name, world)

    # -- host path (single process or explicit buffers) ---------------

    def compressed_allreduce(self, buffer_m, worker_error, server_error,
                             local_rank=None):
        """Reference-signature compressed allreduce (`nccl.py:47`).

        ``buffer_m`` is this rank's flat momentum buffer; in a
        single-process TPU program every rank's buffer lives in the same
        process, so `buffer_m` may be a list of per-rank buffers. Returns
        the updated buffer(s) and mutates nothing.
        """
        from .compressed import compressed_allreduce_two_phase_host

        single = not isinstance(buffer_m, (list, tuple))
        buffers = [jnp.asarray(b, jnp.float32)
                   for b in ([buffer_m] if single else buffer_m)]
        errors = [jnp.asarray(e, jnp.float32)
                  for e in ([worker_error] if single else worker_error)]
        world = len(buffers)
        n = buffers[0].shape[0]
        # zero-pad to a world-divisible length so server chunking never
        # drops elements (arbitrary n, like the pre-chunked behavior)
        pad = (-n) % world
        if pad:
            buffers = [jnp.pad(b, (0, pad)) for b in buffers]
            errors = [jnp.pad(e, (0, pad)) for e in errors]
        chunk = (n + pad) // world
        if isinstance(server_error, (list, tuple)):
            server_errors = [jnp.asarray(e, jnp.float32)
                             for e in server_error]
            if server_errors[0].shape[0] != chunk:
                raise ValueError(
                    f"server_error chunks must be length {chunk} "
                    f"(n={n} padded over world={world}); got "
                    f"{server_errors[0].shape[0]}")
        else:
            # one flat buffer → per-rank server chunks (padded domain)
            se = jnp.asarray(server_error, jnp.float32)
            se = jnp.pad(se, (0, world * chunk - se.shape[0]))
            server_errors = list(se.reshape(world, chunk))

        outs, new_worker_errors, new_server_errors = \
            compressed_allreduce_two_phase_host(buffers, errors,
                                                server_errors, n_valid=n)
        if pad:
            outs = [o[:n] for o in outs]
            new_worker_errors = [e[:n] for e in new_worker_errors]
        if single:
            return outs[0], new_worker_errors[0], new_server_errors[0]
        return outs, new_worker_errors, new_server_errors
