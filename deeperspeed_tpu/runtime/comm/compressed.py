"""Compressed collectives (reference: `deepspeed/runtime/comm/nccl.py:47`,
`mpi.py`, `runtime/compression/cupy.py`).

The reference's `compressed_allreduce` packs sign bits with cupy and moves
them via all_to_all + allgather in two error-compensated phases. Here the
same *numerics* — sign+scale quantization with server-side error feedback —
run as dense XLA collectives over a mesh axis:

    phase 1 (worker):  c = sign(x + err_w); scale = mean|x + err_w|
                       err_w' = (x + err_w) - scale * c
    phase 2 (server):  s = psum_scatter(scale * c) / world   (server chunk)
                       c2 = sign(s + err_s); scale2 = mean|s + err_s|
                       err_s' = (s + err_s) - scale2 * c2
                       out = all_gather(scale2 * c2)

On TPU the bit-packing itself is a bandwidth optimization the ICI fabric
rarely needs; parity targets the *convergence-relevant* quantization
semantics. A packed-int8 transport can be swapped in under the same API.
"""

import jax
import jax.numpy as jnp


def _sign_scale(x):
    """Quantize to sign() with an L1-mean magnitude (per tensor)."""
    scale = jnp.mean(jnp.abs(x))
    comp = jnp.where(x >= 0, 1.0, -1.0).astype(x.dtype)
    return comp * scale, x - comp * scale


def compressed_allreduce_dense(x, worker_error, axis_name):
    """Error-compensated 1-bit allreduce, usable inside shard_map.

    Returns (allreduced_tensor, new_worker_error). The server-side error is
    folded into the worker error (single-buffer variant) so state stays one
    pytree per leaf.
    """
    compensated = x + worker_error
    quantized, new_error = _sign_scale(compensated)
    averaged = jax.lax.pmean(quantized, axis_name=axis_name)
    return averaged, new_error


def compressed_allreduce_dense_two_phase(x, worker_error, server_error,
                                         axis_name, n_valid=None):
    """Dense collectives with the reference's FULL two-phase semantics
    (`comm/nccl.py:47-186`): worker sign+scale with error feedback, mean,
    then server-side requantization with its own error buffer. Works on
    arbitrary-shaped leaves inside shard_map or replicated jit (where the
    server phase computes identically on every rank, i.e. one logical
    server). The packed transport (`compressed_allreduce_two_phase`) is
    the wire-optimal variant of the same math for flat buffers.

    ``n_valid`` (static) marks a zero-padded tail in a flat leaf (the
    ZeRO flat-pad master layout): pad lanes are excluded from the
    quantization scales and pinned to exactly 0 in the output and both
    error buffers — otherwise sign(0)=+1 writes ±scale into lanes that
    must stay zero (and would leak into momentum/master tails)."""
    if n_valid is None or n_valid == x.size:
        compensated = x + worker_error
        quantized, new_worker_error = _sign_scale(compensated)
        averaged = (jax.lax.pmean(quantized, axis_name=axis_name)
                    if axis_name is not None else quantized)
        compensated2 = averaged + server_error
        out, new_server_error = _sign_scale(compensated2)
        return out, new_worker_error, new_server_error

    valid = (jnp.arange(x.size) < n_valid).reshape(x.shape).astype(x.dtype)

    def sign_scale_valid(v):
        scale = jnp.sum(jnp.abs(v)) / n_valid
        q = jnp.where(v >= 0, scale, -scale) * valid
        return q, v - q

    compensated = (x + worker_error) * valid
    quantized, new_worker_error = sign_scale_valid(compensated)
    averaged = (jax.lax.pmean(quantized, axis_name=axis_name)
                if axis_name is not None else quantized)
    compensated2 = (averaged + server_error) * valid
    out, new_server_error = sign_scale_valid(compensated2)
    return out, new_worker_error, new_server_error


def _sign_scale_parts(compensated, valid=None):
    """`_sign_scale_masked` with the wire ingredients exposed:
    (scale, signs, q, new_error) where ``signs`` is the boolean sign
    plane (what the packed transport actually ships, 8/byte) and
    ``q = where(signs, scale, -scale) [* valid]``. Both wire variants
    derive from the SAME (q, new_error) — the error-feedback state is
    computed before the collective and is bit-identical packed or
    dense."""
    signs = compensated >= 0
    if valid is None:
        scale = jnp.mean(jnp.abs(compensated))
        q = jnp.where(signs, scale, -scale)
    else:
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)
        scale = jnp.sum(jnp.abs(compensated)) / n_valid
        q = jnp.where(signs, scale, -scale) * valid
    return scale, signs, q, compensated - q


def _sign_scale_masked(compensated, valid=None):
    """The quantization law shared by the reduce-scatter transport and
    its host oracle: sign() with an L1-mean magnitude over the VALID
    lanes only. ``valid`` (0/1 mask, or None = all valid) marks
    flat-pad tails: pad lanes must quantize to exactly 0 — sign(0)=+1
    would write ±scale into lanes whose cotangents are exact zeros
    (`LayerPlan` rebuild slices them away) and leak into grad norms and
    the flat-padded Adam moment/master tails (the hazard
    `compressed_allreduce_dense_two_phase` documents)."""
    _, _, q, new_error = _sign_scale_parts(compensated, valid)
    return q, new_error


# Process-global default for the reduce-scatter wire variant. The
# engine PINS this to its config every init (same discipline as
# `runtime.pipe.p2p.configure`): modules must not inherit a previous
# engine's wire in the same process.
_PACKED_WIRE = False


def configure_packed_wire(packed=False):
    """Pin the module default for `compressed_reduce_scatter`'s wire:
    True ships 8 packed signs/byte + one fp32 scale per rank
    (all_to_all + all_gather), False ships the dense psum_scatter.
    Armed from `quantization.gradient_compression.packed_wire` or
    `multislice.dcn.packed_wire` — over a DCN fabric the 8x byte
    reduction is the difference between hidden and exposed wire time."""
    global _PACKED_WIRE
    _PACKED_WIRE = bool(packed)


def packed_wire_enabled():
    return _PACKED_WIRE


def compressed_reduce_scatter(x, worker_error, axis_name, world,
                              valid=None, packed=None):
    """Error-compensated 1-bit **reduce-scatter** — the worker phase of
    the reference's two-phase allreduce without the server broadcast,
    which is exactly what the explicit ZeRO-3 schedule needs at the
    layer-backward boundary: each rank contributes a full-size gradient
    buffer and keeps only ITS shard of the sum (the gather transpose's
    `psum_scatter`), so the server requantization/allgather of the
    allreduce variant has no consumer.

    Args (inside shard_map over ``axis_name``):
      x: [world, S] this rank's full cotangent of one gathered layer row
         (chunk j is rank j's shard-gradient contribution).
      worker_error: [world, S] fp32 error-feedback buffer (rank-local).
      valid: optional static [world, S] 0/1 mask of REAL lanes —
        flat-pad tails are excluded from the scale and pinned to 0 in
        the output and the error buffer (`_sign_scale_masked`).
      packed: wire variant — None defers to the module default
        (`configure_packed_wire`). False ships the quantized fp32
        values over a dense `psum_scatter` (4·n bytes; parity targets
        the quantization numerics — the original transport
        discipline). True ships the ACTUAL 1-bit wire: 8 packed
        signs/byte via all_to_all plus one fp32 scale per rank via
        all_gather — ≈ n/8 + 4·world bytes, 8x fewer than the dense
        wire's quantized floats and ~32x fewer than an uncompressed
        reduce-scatter, which is what makes the cross-slice dp
        reduction DCN-rated (docs/multislice.md).
    Returns ([S] sign-compressed rank-SUM of this rank's chunk,
    new_worker_error). Both wires reconstruct the same per-source
    `±scale` values, so outputs differ only in floating-point summation
    order; the error buffer is computed BEFORE the collective and is
    bit-identical — packed vs dense resume states are interchangeable.
    """
    if packed is None:
        packed = _PACKED_WIRE
    compensated = x.astype(jnp.float32) + worker_error
    if valid is not None:
        compensated = compensated * valid
    scale, signs, quantized, new_error = _sign_scale_parts(compensated,
                                                           valid)
    if axis_name is None or world == 1:
        return quantized.reshape(-1), new_error
    if not packed:
        out = jax.lax.psum_scatter(quantized, axis_name,
                                   scatter_dimension=0, tiled=True)
        return out.reshape(-1), new_error

    # packed wire: chunk j of [world, S] belongs to rank j, so the sign
    # planes all_to_all along the chunk dim (this rank keeps every
    # source's chunk `rank`) and the scalar scales all_gather — the
    # `compressed_allreduce_two_phase` phase-1 transport, minus the
    # server requantization the reduce-scatter has no consumer for.
    s = x.shape[-1]
    s8 = -(-s // 8) * 8
    if s8 != s:
        signs = jnp.pad(signs, ((0, 0), (0, s8 - s)))
    wire = pack_signs(signs)                                # [w, s8/8] u8
    recv = jax.lax.all_to_all(wire, axis_name, 0, 0, tiled=False)
    recv = recv.reshape(world, s8 // 8)
    scales = jax.lax.all_gather(scale, axis_name)           # [w] f32
    vals = unpack_signs(recv)[:, :s] * scales[:, None]      # [w, s]
    if valid is not None:
        # every source's chunk `rank` shares the plan-static mask row
        # `rank`; pad lanes' sign bits are wire noise until re-masked
        rank = jax.lax.axis_index(axis_name)
        vals = vals * jax.lax.dynamic_slice_in_dim(valid, rank, 1, 0)
    return jnp.sum(vals, axis=0), new_error


def compressed_reduce_scatter_host(xs, worker_errors, valid=None):
    """Single-process oracle of `compressed_reduce_scatter` (one
    [world, S] buffer per simulated rank): returns (per-rank [S] output
    chunks, new per-rank worker errors)."""
    world = len(xs)
    quantized, new_errors = [], []
    for x, err in zip(xs, worker_errors):
        compensated = jnp.asarray(x, jnp.float32) + err
        if valid is not None:
            compensated = compensated * valid
        q, e = _sign_scale_masked(compensated, valid)
        quantized.append(q)
        new_errors.append(e)
    outs = [sum(q[r] for q in quantized) for r in range(world)]
    return outs, new_errors


def pack_signs(bits):
    """Pack a sign-bit array (bool/int, last dim % 8 == 0) into uint8 —
    the XLA equivalent of the reference's cupy bit packing
    (`runtime/compression/cupy.py`): 8 signs per byte on the wire."""
    n = bits.shape[-1]
    b = bits.reshape(bits.shape[:-1] + (n // 8, 8)).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))
    return jnp.sum(b * weights, axis=-1).astype(jnp.uint8)


def unpack_signs(packed, dtype=jnp.float32):
    """uint8 [..., M] → ±1 values [..., M*8]."""
    bits = (packed[..., None].astype(jnp.uint32) >>
            jnp.arange(8, dtype=jnp.uint32)) & 1
    flat = bits.reshape(packed.shape[:-1] + (packed.shape[-1] * 8,))
    return flat.astype(dtype) * 2 - 1


def wire_pad(n, world):
    """Padded length so a flat buffer splits into `world` byte-aligned
    sign chunks."""
    align = world * 8
    return -(-n // align) * align


def compressed_allreduce_two_phase(x, worker_error, server_error,
                                   axis_name, world, n_valid=None):
    """The reference's ACTUAL transport (`comm/nccl.py:47-186`), inside
    shard_map: packed sign bits move via all_to_all (worker→server
    chunks) and all_gather (server results), with two-phase error
    feedback. Wire volume per step ≈ 2·n/8 bytes of signs + 2·world
    fp32 scales — ~16× less than a ring fp32 allreduce's 2·n·4 bytes.

    Args (all rank-local, inside shard_map over `axis_name`):
      x: flat [n] tensor, n % (world·8) == 0 (see `wire_pad`).
      worker_error: [n] phase-1 error-feedback buffer.
      server_error: [n // world] phase-2 (server-chunk) error buffer.
      n_valid: static count of real elements; lanes >= n_valid are a
        zero-padded tail (ragged lengths), excluded from both phases'
        quantization scales and pinned to 0 in outputs and errors —
        mirroring the host oracle `compressed_allreduce_two_phase_host`.
    Returns (allreduced [n], new_worker_error, new_server_error).
    """
    n = x.shape[0]
    chunk = n // world
    if n_valid is None:
        n_valid = n
    valid = (jnp.arange(n) < n_valid).astype(x.dtype)

    # phase 1: worker quantization with error feedback
    compensated = (x + worker_error) * valid
    scale = jnp.sum(jnp.abs(compensated)) / n_valid
    signs = compensated >= 0
    new_worker_error = compensated - jnp.where(signs, scale, -scale) * valid
    packed = pack_signs(signs.reshape(world, chunk))          # [w, c/8] u8
    recv = jax.lax.all_to_all(packed, axis_name, 0, 0, tiled=False)
    recv = recv.reshape(world, chunk // 8)
    scales = jax.lax.all_gather(scale, axis_name)             # [w] f32

    # phase 2: server average + requantization with server error.
    # This rank serves chunk lanes [rank*chunk, rank*chunk + chunk).
    rank = jax.lax.axis_index(axis_name)
    vchunk = jax.lax.dynamic_slice(valid, (rank * chunk,), (chunk,))
    n_chunk_valid = jnp.maximum(jnp.sum(vchunk), 1.0)
    # pad lanes' sign bits unpack to +1; re-mask before averaging
    vals = unpack_signs(recv) * scales[:, None] * vchunk      # [w, c]
    mean = jnp.mean(vals, axis=0)
    compensated2 = (mean + server_error) * vchunk
    scale2 = jnp.sum(jnp.abs(compensated2)) / n_chunk_valid
    signs2 = compensated2 >= 0
    new_server_error = compensated2 - \
        jnp.where(signs2, scale2, -scale2) * vchunk
    packed2 = pack_signs(signs2[None, :])[0]                  # [c/8] u8
    all_packed = jax.lax.all_gather(packed2, axis_name)       # [w, c/8]
    all_scales = jax.lax.all_gather(scale2, axis_name)        # [w]
    out = (unpack_signs(all_packed) * all_scales[:, None]).reshape(n)
    return out * valid, new_worker_error, new_server_error


def packed_flat_two_phase(m_list, valid_sizes, worker_error, server_error,
                          axis_name, world):
    """ONE packed wire for a whole optimizer step: every leaf's VALID
    momentum prefix concatenates into a single flat buffer, one
    two-phase sign allreduce runs (one all_to_all + one all_gather pair
    per step), and the result splits back per leaf (pad tails restored
    as zeros). The reference compresses one flattened fused buffer the
    same way (`onebit/adam.py:158-175`); per-leaf wires pay
    per-collective latency on every bias/LN scale.

    Args (inside shard_map over `axis_name`):
      m_list: leaf momentum arrays (natural or flat-pad shapes).
      valid_sizes: static per-leaf count of REAL elements (pad_info
        numel for flat-padded ZeRO leaves, size otherwise).
      worker_error: [wire_pad(total, world)] flat buffer.
      server_error: [wire_pad(total, world) // world] buffer.
    Returns (synced leaf list, new_worker_error, new_server_error).
    """
    total = int(sum(valid_sizes))
    pad = wire_pad(total, world)
    flat = jnp.concatenate(
        [jnp.ravel(m)[:nv] for m, nv in zip(m_list, valid_sizes)])
    flat = jnp.pad(flat, (0, pad - total))
    out, e2, s2 = compressed_allreduce_two_phase(
        flat, worker_error, server_error, axis_name, world,
        n_valid=total)
    outs, off = [], 0
    for m, nv in zip(m_list, valid_sizes):
        seg = out[off:off + nv]
        if nv < m.size:
            seg = jnp.pad(seg, (0, m.size - nv))
        outs.append(seg.reshape(m.shape))
        off += nv
    return outs, e2, s2


def compressed_allreduce_two_phase_host(buffers, worker_errors,
                                        server_errors, n_valid=None):
    """Single-process reference of the two-phase math (one array per
    simulated rank) — the oracle the in-mesh transport is tested
    against. ``n_valid`` < n marks a zero-padded tail (ragged lengths):
    pads are excluded from both quantization scales and contribute
    exactly 0, so they cannot distort the real elements' requantization.
    """
    world = len(buffers)
    n = buffers[0].shape[0]
    chunk = n // world
    if n_valid is None:
        n_valid = n
    valid = (jnp.arange(n) < n_valid).astype(jnp.float32)

    quantized, new_worker_errors = [], []
    for buf, err in zip(buffers, worker_errors):
        compensated = (jnp.asarray(buf, jnp.float32) + err) * valid
        scale = jnp.sum(jnp.abs(compensated)) / n_valid
        signs = compensated >= 0
        q = jnp.where(signs, scale, -scale) * valid
        quantized.append(q)
        new_worker_errors.append(compensated - q)

    out_chunks, new_server_errors = [None] * world, []
    for s in range(world):
        vchunk = valid[s * chunk:(s + 1) * chunk]
        n_chunk_valid = jnp.maximum(jnp.sum(vchunk), 1.0)
        vals = jnp.stack([q[s * chunk:(s + 1) * chunk] for q in quantized])
        mean = jnp.mean(vals, axis=0)
        compensated2 = (mean + server_errors[s]) * vchunk
        scale2 = jnp.sum(jnp.abs(compensated2)) / n_chunk_valid
        signs2 = compensated2 >= 0
        out = jnp.where(signs2, scale2, -scale2) * vchunk
        new_server_errors.append(compensated2 - out)
        out_chunks[s] = out
    full = jnp.concatenate(out_chunks)
    return ([full] * world, new_worker_errors, new_server_errors)


def compressed_allreduce_host(tensors, worker_errors, world=1):
    """Host-side (single-process) reference implementation for tests."""
    outs, errs = [], []
    quantized = []
    for x, err in zip(tensors, worker_errors):
        comp = x + err
        q, e = _sign_scale(comp)
        quantized.append(q)
        errs.append(e)
    mean = sum(quantized) / len(quantized)
    for _ in tensors:
        outs.append(mean)
    return outs, errs
