"""Compressed collectives (reference: `deepspeed/runtime/comm/nccl.py:47`,
`mpi.py`, `runtime/compression/cupy.py`).

The reference's `compressed_allreduce` packs sign bits with cupy and moves
them via all_to_all + allgather in two error-compensated phases. Here the
same *numerics* — sign+scale quantization with server-side error feedback —
run as dense XLA collectives over a mesh axis:

    phase 1 (worker):  c = sign(x + err_w); scale = mean|x + err_w|
                       err_w' = (x + err_w) - scale * c
    phase 2 (server):  s = psum_scatter(scale * c) / world   (server chunk)
                       c2 = sign(s + err_s); scale2 = mean|s + err_s|
                       err_s' = (s + err_s) - scale2 * c2
                       out = all_gather(scale2 * c2)

On TPU the bit-packing itself is a bandwidth optimization the ICI fabric
rarely needs; parity targets the *convergence-relevant* quantization
semantics. A packed-int8 transport can be swapped in under the same API.
"""

import jax
import jax.numpy as jnp


def _sign_scale(x):
    """Quantize to sign() with an L1-mean magnitude (per tensor)."""
    scale = jnp.mean(jnp.abs(x))
    comp = jnp.where(x >= 0, 1.0, -1.0).astype(x.dtype)
    return comp * scale, x - comp * scale


def compressed_allreduce_dense(x, worker_error, axis_name):
    """Error-compensated 1-bit allreduce, usable inside shard_map.

    Returns (allreduced_tensor, new_worker_error). The server-side error is
    folded into the worker error (single-buffer variant) so state stays one
    pytree per leaf.
    """
    compensated = x + worker_error
    quantized, new_error = _sign_scale(compensated)
    averaged = jax.lax.pmean(quantized, axis_name=axis_name)
    return averaged, new_error


def compressed_allreduce_host(tensors, worker_errors, world=1):
    """Host-side (single-process) reference implementation for tests."""
    outs, errs = [], []
    quantized = []
    for x, err in zip(tensors, worker_errors):
        comp = x + err
        q, e = _sign_scale(comp)
        quantized.append(q)
        errs.append(e)
    mean = sum(quantized) / len(quantized)
    for _ in tensors:
        outs.append(mean)
    return outs, errs
