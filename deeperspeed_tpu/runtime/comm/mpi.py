"""`MpiBackend`-compatible compressed-allreduce backend.

Reference: `deepspeed/runtime/comm/mpi.py:14` — the mpi4py variant of the
1-bit compressed allreduce, with an optional CUDA-aware fast path. On TPU
multi-host jobs the transport under `jax.distributed` is the same ICI/DCN
fabric the NCCL-shaped backend uses, so this class shares the math with
`NcclBackend` and exists for API parity (user code selects backends by
name: `comm_backend_name: "mpi"`).
"""

from .nccl import NcclBackend


class MpiBackend(NcclBackend):
    """Same compressed-allreduce semantics; `cuda_aware` accepted and
    ignored (no host staging distinction on TPU — transfers are DMA'd by
    the runtime either way)."""

    def __init__(self, cuda_aware=False, mpu=None, axis_name="data"):
        super().__init__(mpu=mpu, axis_name=axis_name)
        self.cuda_aware = cuda_aware
