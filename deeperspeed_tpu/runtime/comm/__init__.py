from .compressed import (compressed_allreduce_dense,
                         compressed_allreduce_host)
from .nccl import NcclBackend
from .mpi import MpiBackend

__all__ = ["compressed_allreduce_dense", "compressed_allreduce_host",
           "NcclBackend", "MpiBackend"]
