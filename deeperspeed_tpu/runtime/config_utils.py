"""Config parsing helpers (reference: `deepspeed/runtime/config_utils.py`)."""

import json


class DeepSpeedConfigError(Exception):
    """Raised when a config file is malformed or internally inconsistent."""


def _reject_duplicate_keys(pairs):
    seen = {}
    for key, value in pairs:
        if key in seen:
            raise DeepSpeedConfigError(
                f"Duplicate key '{key}' in DeepSpeed config JSON")
        seen[key] = value
    return seen


def load_config_json(path):
    """Load a config JSON file, rejecting duplicate keys."""
    with open(path, "r") as f:
        return json.load(f, object_pairs_hook=_reject_duplicate_keys)


def loads_config_json(text):
    return json.loads(text, object_pairs_hook=_reject_duplicate_keys)


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name):
    value = param_dict.get(param_name)
    return dict(value) if isinstance(value, dict) else None


def as_int(value, name):
    """Coerce JSON numerics like 5e8 to int; reject non-integral values."""
    if value is None or isinstance(value, bool):
        raise DeepSpeedConfigError(f"'{name}' must be an integer, got {value!r}")
    try:
        ivalue = int(value)
    except (TypeError, ValueError):
        raise DeepSpeedConfigError(
            f"'{name}' must be an integer, got {value!r}") from None
    if float(ivalue) != float(value):
        raise DeepSpeedConfigError(
            f"'{name}' must be integral, got {value!r}")
    return ivalue


def strict_positive_int(param_dict, key, default, scope):
    """Checkpoint-block-strict positive-int knob: coerces JSON numerics,
    rejects < 1. ``scope`` prefixes the error ('aio',
    'zero_optimization.offload_param', ...)."""
    value = as_int(get_scalar_param(param_dict, key, default),
                   f"{scope}.{key}")
    if value < 1:
        raise DeepSpeedConfigError(
            f"'{scope}.{key}' must be a positive integer, got {value}")
    return value


def strict_bool(param_dict, key, default, scope):
    """Checkpoint-block-strict boolean knob: only real JSON booleans
    pass ('true'/1 must not silently truthy-coerce)."""
    value = get_scalar_param(param_dict, key, default)
    if not isinstance(value, bool):
        raise DeepSpeedConfigError(
            f"'{scope}.{key}' must be a boolean, got {value!r}")
    return value


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Alias kept for parity with the reference helper name."""
    return _reject_duplicate_keys(ordered_pairs)
