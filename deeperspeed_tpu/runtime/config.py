"""DeepSpeed-schema JSON config → typed config object.

Reference: `deepspeed/runtime/config.py:536` (`DeepSpeedConfig`), including
the batch-triad resolution of `_set_batch_related_parameters`
(`config.py:701`). The JSON schema is the compatibility surface — GPT-NeoX
configs must parse unmodified — but the object model here is dataclass-based
rather than the reference's getter functions.
"""

import jax.numpy as jnp

from ..elasticity import (compute_elastic_config, elasticity_enabled,
                          ensure_immutable_elastic_config)
from ..elasticity.constants import (ELASTICITY,
                                    IGNORE_NON_ELASTIC_BATCH_INFO,
                                    IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
from ..profiling.config import DeepSpeedFlopsProfilerConfig
from ..utils.logging import logger
from ..version import __version__
from . import constants as c
from .activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig)
from .config_utils import (DeepSpeedConfigError, as_int, get_scalar_param,
                           load_config_json)
from .precision import needs_loss_scaling, resolve_precision
from .swap_tensor.aio_config import DeepSpeedAIOConfig
from .zero.config import DeepSpeedZeroConfig

TENSOR_CORE_ALIGN_SIZE = 8
ADAM_OPTIMIZER = "adam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER,
]


def _parse_sparse_attention(param_dict):
    """Parse the "sparse_attention" block into a plain dict of knobs
    (reference `config.py:213-383`)."""
    sparsity = param_dict.get(c.SPARSE_ATTENTION)
    if sparsity is None:
        return None
    mode = get_scalar_param(sparsity, c.SPARSE_MODE, c.SPARSE_MODE_DEFAULT)

    common = {
        c.SPARSE_MODE: mode,
        c.SPARSE_BLOCK: get_scalar_param(sparsity, c.SPARSE_BLOCK,
                                         c.SPARSE_BLOCK_DEFAULT),
        c.SPARSE_DIFFERENT_LAYOUT_PER_HEAD: get_scalar_param(
            sparsity, c.SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
            c.SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
    }
    if mode == c.SPARSE_DENSE_MODE:
        return common
    if mode == c.SPARSE_FIXED_MODE:
        extra = {
            c.SPARSE_NUM_LOCAL_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_LOCAL_BLOCKS,
                c.SPARSE_NUM_LOCAL_BLOCKS_DEFAULT),
            c.SPARSE_NUM_GLOBAL_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_GLOBAL_BLOCKS,
                c.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
            # unset stays None: the consumer picks its default —
            # the SparsityConfig constructors keep the reference's
            # bidirectional, the causal-LM sparse engine (gpt_neox.
            # make_sparse_attention) needs unidirectional and must be
            # able to tell "user asked for bidirectional" apart
            c.SPARSE_ATTENTION_TYPE: get_scalar_param(
                sparsity, c.SPARSE_ATTENTION_TYPE, None),
            c.SPARSE_HORIZONTAL_GLOBAL_ATTENTION: get_scalar_param(
                sparsity, c.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                c.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
            c.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS: get_scalar_param(
                sparsity, c.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
                c.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT),
        }
    elif mode == c.SPARSE_VARIABLE_MODE:
        extra = {
            c.SPARSE_NUM_RANDOM_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_RANDOM_BLOCKS,
                c.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
            c.SPARSE_LOCAL_WINDOW_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_LOCAL_WINDOW_BLOCKS,
                c.SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT),
            c.SPARSE_GLOBAL_BLOCK_INDICES: get_scalar_param(
                sparsity, c.SPARSE_GLOBAL_BLOCK_INDICES,
                c.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
            c.SPARSE_GLOBAL_BLOCK_END_INDICES: get_scalar_param(
                sparsity, c.SPARSE_GLOBAL_BLOCK_END_INDICES,
                c.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
            # unset stays None: the consumer picks its default —
            # the SparsityConfig constructors keep the reference's
            # bidirectional, the causal-LM sparse engine (gpt_neox.
            # make_sparse_attention) needs unidirectional and must be
            # able to tell "user asked for bidirectional" apart
            c.SPARSE_ATTENTION_TYPE: get_scalar_param(
                sparsity, c.SPARSE_ATTENTION_TYPE, None),
            c.SPARSE_HORIZONTAL_GLOBAL_ATTENTION: get_scalar_param(
                sparsity, c.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                c.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
        }
    elif mode == c.SPARSE_BIGBIRD_MODE:
        extra = {
            c.SPARSE_NUM_RANDOM_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_RANDOM_BLOCKS,
                c.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
            c.SPARSE_NUM_SLIDING_WINDOW_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                c.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
            c.SPARSE_NUM_GLOBAL_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_GLOBAL_BLOCKS,
                c.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
        }
    elif mode == c.SPARSE_BSLONGFORMER_MODE:
        extra = {
            c.SPARSE_NUM_SLIDING_WINDOW_BLOCKS: get_scalar_param(
                sparsity, c.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                c.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
            c.SPARSE_GLOBAL_BLOCK_INDICES: get_scalar_param(
                sparsity, c.SPARSE_GLOBAL_BLOCK_INDICES,
                c.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
            c.SPARSE_GLOBAL_BLOCK_END_INDICES: get_scalar_param(
                sparsity, c.SPARSE_GLOBAL_BLOCK_END_INDICES,
                c.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
        }
    else:
        raise DeepSpeedConfigError(
            f"Invalid sparse_attention mode {mode!r}")
    common.update(extra)
    return common


def parse_planner_block(d):
    """Parse + validate the "planner" block (the profile-guided
    schedule planner, `deeperspeed_tpu/planner`; docs/planner.md) at
    checkpoint-block strictness. Module-level so `ds_plan` tooling can
    validate raw dicts identically.

    Returns the validated params dict, or None when the block is
    absent. `plan_file` is REQUIRED when enabled: the engine has no
    model-shape key at config-parse time, so there is no implicit
    cache lookup to fall back on — a planner block that silently
    applied nothing would be the parse-only-key bug class."""
    block = d.get(c.PLANNER)
    if block is None:
        return None
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"'{c.PLANNER}' must be a dict, got {block!r}")
    known = {c.PLANNER_ENABLED, c.PLANNER_PLAN_FILE,
             c.PLANNER_STRICT_DEVICE_MATCH}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown '{c.PLANNER}' key(s) {unknown}; valid keys: "
            f"{sorted(known)}")
    enabled = block.get(c.PLANNER_ENABLED, c.PLANNER_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"{c.PLANNER}.{c.PLANNER_ENABLED} must be a boolean, got "
            f"{enabled!r}")
    plan_file = block.get(c.PLANNER_PLAN_FILE,
                          c.PLANNER_PLAN_FILE_DEFAULT)
    if plan_file is not None and not isinstance(plan_file, str):
        raise DeepSpeedConfigError(
            f"{c.PLANNER}.{c.PLANNER_PLAN_FILE} must be a string path "
            f"to a ds_plan-emitted plan, got {plan_file!r}")
    strict = block.get(c.PLANNER_STRICT_DEVICE_MATCH,
                       c.PLANNER_STRICT_DEVICE_MATCH_DEFAULT)
    if not isinstance(strict, bool):
        raise DeepSpeedConfigError(
            f"{c.PLANNER}.{c.PLANNER_STRICT_DEVICE_MATCH} must be a "
            f"boolean, got {strict!r}")
    if enabled and plan_file is None:
        raise DeepSpeedConfigError(
            f"{c.PLANNER}.{c.PLANNER_PLAN_FILE} is required when the "
            f"block is enabled (emit one with: ds_plan --preset 125m)")
    return {
        c.PLANNER_ENABLED: enabled,
        c.PLANNER_PLAN_FILE: plan_file,
        c.PLANNER_STRICT_DEVICE_MATCH: strict,
    }


def parse_inference_block(d):
    """Parse + validate the "inference" block (the serving engine,
    `deeperspeed_tpu/inference`). Module-level so `InferenceEngine` can
    validate a raw config dict without the training-side batch triad;
    `DeepSpeedConfig` delegates here. Same parse-time strictness as the
    "checkpoint" block: a mistyped bucket ladder must fail at engine
    init, not recompile (or OOM the page pool) under live traffic.

    Returns the validated params dict, or False when absent/disabled."""
    inf = d.get(c.INFERENCE) or {}
    known = {c.INFERENCE_ENABLED, c.INFERENCE_PAGE_SIZE,
             c.INFERENCE_NUM_PAGES, c.INFERENCE_MAX_SEQ_LEN,
             c.INFERENCE_MAX_BATCH_SIZE, c.INFERENCE_TOKEN_BUDGET,
             c.INFERENCE_PREFILL_LENGTHS, c.INFERENCE_PREFILL_BATCH_SIZES,
             c.INFERENCE_DECODE_BATCH_SIZES, c.INFERENCE_TEMPERATURE,
             c.INFERENCE_SEED, c.INFERENCE_KERNEL, c.INFERENCE_KV_DTYPE,
             c.INFERENCE_DRAIN_DEADLINE, c.INFERENCE_DEFAULT_PRIORITY,
             c.INFERENCE_HANG_TIMEOUT, c.INFERENCE_ADMISSION,
             c.INFERENCE_RETRY, c.INFERENCE_FAULT_INJECTION,
             c.INFERENCE_PREFIX_CACHE, c.INFERENCE_SPECULATIVE,
             c.INFERENCE_DISAGGREGATION, c.INFERENCE_ROUTER}
    unknown = sorted(set(inf) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference' key(s) {unknown}; valid keys: "
            f"{sorted(known)}")

    enabled = inf.get(c.INFERENCE_ENABLED, c.INFERENCE_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_ENABLED} must be a boolean, got "
            f"{enabled!r}")
    if not enabled:
        return False

    ints = {}
    for key, default, lo in (
            (c.INFERENCE_PAGE_SIZE, c.INFERENCE_PAGE_SIZE_DEFAULT, 8),
            (c.INFERENCE_NUM_PAGES, c.INFERENCE_NUM_PAGES_DEFAULT, 2),
            (c.INFERENCE_MAX_BATCH_SIZE,
             c.INFERENCE_MAX_BATCH_SIZE_DEFAULT, 1),
            (c.INFERENCE_TOKEN_BUDGET,
             c.INFERENCE_TOKEN_BUDGET_DEFAULT, 1),
            (c.INFERENCE_SEED, c.INFERENCE_SEED_DEFAULT, 0)):
        value = as_int(inf.get(key, default), f"inference.{key}")
        if value < lo:
            raise DeepSpeedConfigError(
                f"inference.{key} must be >= {lo}, got {value}")
        ints[key] = value
    if ints[c.INFERENCE_PAGE_SIZE] % 8:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_PAGE_SIZE} must be a multiple of 8 "
            f"(TPU sublane tile), got {ints[c.INFERENCE_PAGE_SIZE]}")

    max_seq_len = inf.get(c.INFERENCE_MAX_SEQ_LEN,
                          c.INFERENCE_MAX_SEQ_LEN_DEFAULT)
    if max_seq_len is not None:
        max_seq_len = as_int(max_seq_len,
                             f"inference.{c.INFERENCE_MAX_SEQ_LEN}")
        if max_seq_len < 1:
            raise DeepSpeedConfigError(
                f"inference.{c.INFERENCE_MAX_SEQ_LEN} must be >= 1, got "
                f"{max_seq_len}")

    def bucket_list(key, minimum=1):
        raw = inf.get(key)
        if raw is None:
            return None
        if not isinstance(raw, (list, tuple)) or not raw:
            raise DeepSpeedConfigError(
                f"inference.{key} must be a non-empty list of ints, got "
                f"{raw!r}")
        vals = [as_int(v, f"inference.{key}") for v in raw]
        if any(v < minimum for v in vals):
            raise DeepSpeedConfigError(
                f"inference.{key} entries must be >= {minimum}, got "
                f"{vals}")
        if sorted(vals) != vals or len(set(vals)) != len(vals):
            raise DeepSpeedConfigError(
                f"inference.{key} must be strictly increasing, got "
                f"{vals}")
        return vals

    prefill_lengths = bucket_list(c.INFERENCE_PREFILL_LENGTHS)
    if prefill_lengths is not None:
        bad = [v for v in prefill_lengths
               if v % ints[c.INFERENCE_PAGE_SIZE]]
        if bad:
            raise DeepSpeedConfigError(
                f"inference.{c.INFERENCE_PREFILL_LENGTHS} entries must "
                f"be multiples of page_size "
                f"{ints[c.INFERENCE_PAGE_SIZE]} (the prefill scatter "
                f"writes whole pages), got {bad}")
    prefill_batch_sizes = bucket_list(c.INFERENCE_PREFILL_BATCH_SIZES)
    decode_batch_sizes = bucket_list(c.INFERENCE_DECODE_BATCH_SIZES)
    if decode_batch_sizes is not None and \
            decode_batch_sizes[-1] < ints[c.INFERENCE_MAX_BATCH_SIZE]:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_DECODE_BATCH_SIZES} tops out at "
            f"{decode_batch_sizes[-1]} but max_batch_size is "
            f"{ints[c.INFERENCE_MAX_BATCH_SIZE]}: a full continuous "
            f"batch would have no compiled shape")

    temperature = inf.get(c.INFERENCE_TEMPERATURE,
                          c.INFERENCE_TEMPERATURE_DEFAULT)
    if not isinstance(temperature, (int, float)) or \
            isinstance(temperature, bool) or temperature < 0:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_TEMPERATURE} must be a number >= 0 "
            f"(0 = greedy), got {temperature!r}")

    kernel = inf.get(c.INFERENCE_KERNEL, c.INFERENCE_KERNEL_DEFAULT)
    if kernel not in c.INFERENCE_KERNEL_CHOICES:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_KERNEL} must be one of "
            f"{list(c.INFERENCE_KERNEL_CHOICES)}, got {kernel!r}")

    kv_dtype = inf.get(c.INFERENCE_KV_DTYPE, c.INFERENCE_KV_DTYPE_DEFAULT)
    if kv_dtype is not None:
        # validated against the POOL dtypes the paged cache implements,
        # not resolve_precision's full spelling table: an unsupported
        # pool dtype must fail here with the choices listed, not as a
        # late kernel error far from the config
        if not isinstance(kv_dtype, str) or \
                kv_dtype.lower() not in c.INFERENCE_KV_DTYPE_CHOICES:
            raise DeepSpeedConfigError(
                f"inference.{c.INFERENCE_KV_DTYPE} must be null (the "
                f"params' compute dtype) or a supported pool precision "
                f"{sorted(c.INFERENCE_KV_DTYPE_CHOICES)}, got "
                f"{kv_dtype!r}")
        kv_dtype = kv_dtype.lower()
        if kv_dtype == "int8" and kernel == "pallas" and \
                ints[c.INFERENCE_PAGE_SIZE] % 32:
            # the int8 decode kernel needs the int8 sublane tile; with
            # kernel "auto" a misaligned page_size silently takes the
            # XLA fallback (documented), but a FORCED kernel must fail
            # here, not as a Mosaic tiling error at bucket warmup
            raise DeepSpeedConfigError(
                f"inference.kernel \"pallas\" with kv_cache_dtype "
                f"\"int8\" needs page_size % 32 == 0 (the int8 sublane "
                f"tile), got {ints[c.INFERENCE_PAGE_SIZE]}")

    drain_deadline = inf.get(c.INFERENCE_DRAIN_DEADLINE,
                             c.INFERENCE_DRAIN_DEADLINE_DEFAULT)
    if not isinstance(drain_deadline, (int, float)) or \
            isinstance(drain_deadline, bool) or drain_deadline < 0:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_DRAIN_DEADLINE} must be a number "
            f">= 0 (seconds; 0 = stop immediately after the current "
            f"step), got {drain_deadline!r}")

    # -- serving robustness (inference/admission.py) -------------------

    from ..inference.admission import PRIORITIES
    default_priority = inf.get(c.INFERENCE_DEFAULT_PRIORITY,
                               c.INFERENCE_DEFAULT_PRIORITY_DEFAULT)
    if default_priority not in PRIORITIES:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_DEFAULT_PRIORITY} must be one of "
            f"{list(PRIORITIES)}, got {default_priority!r}")

    hang_timeout = inf.get(c.INFERENCE_HANG_TIMEOUT,
                           c.INFERENCE_HANG_TIMEOUT_DEFAULT)
    if not isinstance(hang_timeout, (int, float)) or \
            isinstance(hang_timeout, bool) or hang_timeout < 0:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_HANG_TIMEOUT} must be a number "
            f">= 0 (seconds; 0 = watchdog off), got {hang_timeout!r}")

    admission = _parse_inference_admission(
        inf.get(c.INFERENCE_ADMISSION))
    retry = _parse_inference_retry(inf.get(c.INFERENCE_RETRY))
    prefix_cache = _parse_inference_prefix_cache(
        inf.get(c.INFERENCE_PREFIX_CACHE))
    speculative = _parse_inference_speculative(
        inf.get(c.INFERENCE_SPECULATIVE))
    disaggregation = _parse_inference_disaggregation(
        inf.get(c.INFERENCE_DISAGGREGATION))
    router = _parse_inference_router(inf.get(c.INFERENCE_ROUTER))
    if disaggregation["role"] != "unified" and speculative is not None:
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_DISAGGREGATION} role "
            f"{disaggregation['role']!r} cannot combine with "
            f"inference.{c.INFERENCE_SPECULATIVE}: the draft model's "
            f"shadow KV pools cannot be reconstructed from a page "
            f"handoff yet — run speculation on unified pools")

    fault_spec = inf.get(c.INFERENCE_FAULT_INJECTION)
    if fault_spec is not None:
        from .fault_injection import validate_fault_spec
        validate_fault_spec(fault_spec,
                            where=f"inference.{c.INFERENCE_FAULT_INJECTION}")

    return {
        "page_size": ints[c.INFERENCE_PAGE_SIZE],
        "num_pages": ints[c.INFERENCE_NUM_PAGES],
        "max_seq_len": max_seq_len,
        "max_batch_size": ints[c.INFERENCE_MAX_BATCH_SIZE],
        "token_budget": ints[c.INFERENCE_TOKEN_BUDGET],
        "prefill_lengths": prefill_lengths,
        "prefill_batch_sizes": prefill_batch_sizes,
        "decode_batch_sizes": decode_batch_sizes,
        "temperature": float(temperature),
        "seed": ints[c.INFERENCE_SEED],
        "kernel": kernel,
        "kv_cache_dtype": kv_dtype,
        "drain_deadline_s": float(drain_deadline),
        "default_priority": default_priority,
        "hang_timeout_s": float(hang_timeout),
        "admission": admission,
        "retry": retry,
        "fault_injection": fault_spec,
        "prefix_cache": prefix_cache,
        "speculative": speculative,
        "disaggregation": disaggregation,
        "router": router,
    }


def parse_rl_block(d):
    """Parse + validate the "rl" block (the online-RL driver,
    `deeperspeed_tpu/rl`; docs/rl.md). Module-level so `RLDriver` can
    validate raw dicts identically; `DeepSpeedConfig` delegates here.
    Same parse-time strictness as the "inference" block: a mistyped
    rollout geometry must fail at driver construction, not as a shape
    mismatch (= silent recompile) three iterations into a run.

    Returns the validated params dict, or False when absent/disabled."""
    block = d.get(c.RL) or {}
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"'{c.RL}' must be a dict, got {block!r}")
    known = {c.RL_ENABLED, c.RL_LOSS, c.RL_ROLLOUTS_PER_ITERATION,
             c.RL_GROUP_SIZE, c.RL_MAX_NEW_TOKENS, c.RL_SEQUENCE_LENGTH,
             c.RL_CLIP_RATIO, c.RL_KL_COEF, c.RL_BETA,
             c.RL_CHECKPOINT_INTERVAL}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown '{c.RL}' key(s) {unknown}; valid keys: "
            f"{sorted(known)}")

    enabled = block.get(c.RL_ENABLED, c.RL_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"{c.RL}.{c.RL_ENABLED} must be a boolean, got {enabled!r}")
    if not enabled:
        return False

    loss = block.get(c.RL_LOSS, c.RL_LOSS_DEFAULT)
    if loss not in c.RL_LOSS_CHOICES:
        raise DeepSpeedConfigError(
            f"{c.RL}.{c.RL_LOSS} must be one of "
            f"{list(c.RL_LOSS_CHOICES)}, got {loss!r}")

    ints = {}
    for key, default, lo in (
            (c.RL_ROLLOUTS_PER_ITERATION,
             c.RL_ROLLOUTS_PER_ITERATION_DEFAULT, 1),
            (c.RL_GROUP_SIZE, c.RL_GROUP_SIZE_DEFAULT, 1),
            (c.RL_MAX_NEW_TOKENS, c.RL_MAX_NEW_TOKENS_DEFAULT, 1),
            (c.RL_CHECKPOINT_INTERVAL,
             c.RL_CHECKPOINT_INTERVAL_DEFAULT, 1)):
        value = as_int(block.get(key, default), f"{c.RL}.{key}")
        if value < lo:
            raise DeepSpeedConfigError(
                f"{c.RL}.{key} must be >= {lo}, got {value}")
        ints[key] = value
    if ints[c.RL_ROLLOUTS_PER_ITERATION] % ints[c.RL_GROUP_SIZE]:
        raise DeepSpeedConfigError(
            f"{c.RL}.{c.RL_ROLLOUTS_PER_ITERATION} "
            f"({ints[c.RL_ROLLOUTS_PER_ITERATION]}) must be a multiple "
            f"of {c.RL_GROUP_SIZE} ({ints[c.RL_GROUP_SIZE]}): each "
            f"iteration samples whole prompt groups")
    if loss == "dpo" and ints[c.RL_GROUP_SIZE] < 2:
        raise DeepSpeedConfigError(
            f"{c.RL}.{c.RL_LOSS} \"dpo\" needs {c.RL_GROUP_SIZE} >= 2: "
            f"the chosen/rejected pair is picked within a prompt group")

    seq_len = block.get(c.RL_SEQUENCE_LENGTH, c.RL_SEQUENCE_LENGTH_DEFAULT)
    if seq_len is not None:
        seq_len = as_int(seq_len, f"{c.RL}.{c.RL_SEQUENCE_LENGTH}")
        if seq_len < 2:
            raise DeepSpeedConfigError(
                f"{c.RL}.{c.RL_SEQUENCE_LENGTH} must be >= 2 (next-token "
                f"logprobs need at least one transition), got {seq_len}")

    floats = {}
    for key, default, lo_open in (
            (c.RL_CLIP_RATIO, c.RL_CLIP_RATIO_DEFAULT, True),
            (c.RL_KL_COEF, c.RL_KL_COEF_DEFAULT, False),
            (c.RL_BETA, c.RL_BETA_DEFAULT, True)):
        value = block.get(key, default)
        if not isinstance(value, (int, float)) or \
                isinstance(value, bool) or \
                (value <= 0 if lo_open else value < 0):
            bound = "> 0" if lo_open else ">= 0"
            raise DeepSpeedConfigError(
                f"{c.RL}.{key} must be a number {bound}, got {value!r}")
        floats[key] = float(value)

    return {
        c.RL_ENABLED: True,
        c.RL_LOSS: loss,
        c.RL_ROLLOUTS_PER_ITERATION: ints[c.RL_ROLLOUTS_PER_ITERATION],
        c.RL_GROUP_SIZE: ints[c.RL_GROUP_SIZE],
        c.RL_MAX_NEW_TOKENS: ints[c.RL_MAX_NEW_TOKENS],
        c.RL_SEQUENCE_LENGTH: seq_len,
        c.RL_CLIP_RATIO: floats[c.RL_CLIP_RATIO],
        c.RL_KL_COEF: floats[c.RL_KL_COEF],
        c.RL_BETA: floats[c.RL_BETA],
        c.RL_CHECKPOINT_INTERVAL: ints[c.RL_CHECKPOINT_INTERVAL],
    }


def _parse_inference_admission(block):
    """Validate the ``inference.admission`` sub-block -> params dict,
    or None when absent/disabled (no admission control: the
    pre-robustness unbounded-queue behavior)."""
    if block is None:
        return None
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_ADMISSION} must be an object, got "
            f"{type(block).__name__}")
    known = {c.INFERENCE_ADMISSION_ENABLED,
             c.INFERENCE_ADMISSION_MAX_QUEUE_DEPTH,
             c.INFERENCE_ADMISSION_SHED_POOL_UTIL,
             c.INFERENCE_ADMISSION_SHED_TTFT_EMA,
             c.INFERENCE_ADMISSION_TTFT_EMA_BETA,
             c.INFERENCE_ADMISSION_RETRY_AFTER_CAP}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference.{c.INFERENCE_ADMISSION}' key(s) "
            f"{unknown}; valid keys: {sorted(known)}")
    enabled = block.get(c.INFERENCE_ADMISSION_ENABLED,
                        c.INFERENCE_ADMISSION_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_ADMISSION}."
            f"{c.INFERENCE_ADMISSION_ENABLED} must be a boolean, got "
            f"{enabled!r}")
    if not enabled:
        return None

    where = f"inference.{c.INFERENCE_ADMISSION}"
    depth = as_int(block.get(c.INFERENCE_ADMISSION_MAX_QUEUE_DEPTH,
                             c.INFERENCE_ADMISSION_MAX_QUEUE_DEPTH_DEFAULT),
                   f"{where}.{c.INFERENCE_ADMISSION_MAX_QUEUE_DEPTH}")
    if depth < 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_ADMISSION_MAX_QUEUE_DEPTH} must be "
            f">= 1, got {depth}")

    pool_util = block.get(c.INFERENCE_ADMISSION_SHED_POOL_UTIL,
                          c.INFERENCE_ADMISSION_SHED_POOL_UTIL_DEFAULT)
    if not isinstance(pool_util, (int, float)) or \
            isinstance(pool_util, bool) or not 0 < pool_util <= 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_ADMISSION_SHED_POOL_UTIL} must be a "
            f"number in (0, 1], got {pool_util!r}")

    ttft_ms = block.get(c.INFERENCE_ADMISSION_SHED_TTFT_EMA,
                        c.INFERENCE_ADMISSION_SHED_TTFT_EMA_DEFAULT)
    if ttft_ms is not None and (
            not isinstance(ttft_ms, (int, float)) or
            isinstance(ttft_ms, bool) or ttft_ms <= 0):
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_ADMISSION_SHED_TTFT_EMA} must be a "
            f"number > 0 (milliseconds) or null (signal off), got "
            f"{ttft_ms!r}")

    beta = block.get(c.INFERENCE_ADMISSION_TTFT_EMA_BETA,
                     c.INFERENCE_ADMISSION_TTFT_EMA_BETA_DEFAULT)
    if not isinstance(beta, (int, float)) or isinstance(beta, bool) or \
            not 0 < beta < 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_ADMISSION_TTFT_EMA_BETA} must be a "
            f"number in (0, 1), got {beta!r}")

    cap = block.get(c.INFERENCE_ADMISSION_RETRY_AFTER_CAP,
                    c.INFERENCE_ADMISSION_RETRY_AFTER_CAP_DEFAULT)
    if not isinstance(cap, (int, float)) or isinstance(cap, bool) or \
            cap <= 0:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_ADMISSION_RETRY_AFTER_CAP} must be a "
            f"number > 0 (seconds), got {cap!r}")

    return {"max_queue_depth": depth,
            "shed_page_pool_util": float(pool_util),
            "shed_ttft_ema_ms": (None if ttft_ms is None
                                 else float(ttft_ms)),
            "ttft_ema_beta": float(beta),
            "retry_after_cap_s": float(cap)}


def _parse_inference_retry(block):
    """Validate the ``inference.retry`` sub-block -> params dict. The
    retry/poison machinery is always on (a step failure must never kill
    the server), so an absent block yields the defaults."""
    if block is None:
        block = {}
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_RETRY} must be an object, got "
            f"{type(block).__name__}")
    known = {c.INFERENCE_RETRY_MAX_ATTEMPTS,
             c.INFERENCE_RETRY_BACKOFF_BASE,
             c.INFERENCE_RETRY_BACKOFF_CAP, c.INFERENCE_RETRY_JITTER}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference.{c.INFERENCE_RETRY}' key(s) {unknown}; "
            f"valid keys: {sorted(known)}")
    where = f"inference.{c.INFERENCE_RETRY}"

    attempts = as_int(block.get(c.INFERENCE_RETRY_MAX_ATTEMPTS,
                                c.INFERENCE_RETRY_MAX_ATTEMPTS_DEFAULT),
                      f"{where}.{c.INFERENCE_RETRY_MAX_ATTEMPTS}")
    if attempts < 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_RETRY_MAX_ATTEMPTS} must be >= 1 "
            f"(the first attempt counts), got {attempts}")

    base = block.get(c.INFERENCE_RETRY_BACKOFF_BASE,
                     c.INFERENCE_RETRY_BACKOFF_BASE_DEFAULT)
    cap = block.get(c.INFERENCE_RETRY_BACKOFF_CAP,
                    c.INFERENCE_RETRY_BACKOFF_CAP_DEFAULT)
    for key, value in ((c.INFERENCE_RETRY_BACKOFF_BASE, base),
                       (c.INFERENCE_RETRY_BACKOFF_CAP, cap)):
        if not isinstance(value, (int, float)) or \
                isinstance(value, bool) or value <= 0:
            raise DeepSpeedConfigError(
                f"{where}.{key} must be a number > 0 (milliseconds), "
                f"got {value!r}")
    if cap < base:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_RETRY_BACKOFF_CAP} ({cap}) must be "
            f">= {c.INFERENCE_RETRY_BACKOFF_BASE} ({base})")

    jitter = block.get(c.INFERENCE_RETRY_JITTER,
                       c.INFERENCE_RETRY_JITTER_DEFAULT)
    if not isinstance(jitter, (int, float)) or \
            isinstance(jitter, bool) or not 0 <= jitter < 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_RETRY_JITTER} must be a number in "
            f"[0, 1), got {jitter!r}")

    return {"max_attempts": attempts, "backoff_base_ms": float(base),
            "backoff_cap_ms": float(cap), "jitter": float(jitter)}


def _parse_inference_prefix_cache(block):
    """Validate the ``inference.prefix_cache`` sub-block -> params dict,
    or None when absent/disabled (no cross-request KV reuse: the
    pre-prefix-cache behavior)."""
    if block is None:
        return None
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_PREFIX_CACHE} must be an object, "
            f"got {type(block).__name__}")
    known = {c.INFERENCE_PREFIX_CACHE_ENABLED,
             c.INFERENCE_PREFIX_CACHE_MAX_PAGES}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference.{c.INFERENCE_PREFIX_CACHE}' key(s) "
            f"{unknown}; valid keys: {sorted(known)}")
    enabled = block.get(c.INFERENCE_PREFIX_CACHE_ENABLED,
                        c.INFERENCE_PREFIX_CACHE_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_PREFIX_CACHE}."
            f"{c.INFERENCE_PREFIX_CACHE_ENABLED} must be a boolean, got "
            f"{enabled!r}")
    if not enabled:
        return None

    where = f"inference.{c.INFERENCE_PREFIX_CACHE}"
    max_pages = block.get(c.INFERENCE_PREFIX_CACHE_MAX_PAGES,
                          c.INFERENCE_PREFIX_CACHE_MAX_PAGES_DEFAULT)
    if max_pages is not None:
        max_pages = as_int(
            max_pages, f"{where}.{c.INFERENCE_PREFIX_CACHE_MAX_PAGES}")
        if max_pages < 1:
            raise DeepSpeedConfigError(
                f"{where}.{c.INFERENCE_PREFIX_CACHE_MAX_PAGES} must be "
                f">= 1 or null (registry bounded only by the pool), got "
                f"{max_pages}")

    return {"max_pages": max_pages}


def _parse_inference_speculative(block):
    """Validate the ``inference.speculative`` sub-block -> params dict,
    or None when absent/disabled (plain one-token-per-step decode)."""
    if block is None:
        return None
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_SPECULATIVE} must be an object, "
            f"got {type(block).__name__}")
    known = {c.INFERENCE_SPECULATIVE_ENABLED,
             c.INFERENCE_SPECULATIVE_NUM_DRAFT,
             c.INFERENCE_SPECULATIVE_DRAFT_WEIGHT_QUANT}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference.{c.INFERENCE_SPECULATIVE}' key(s) "
            f"{unknown}; valid keys: {sorted(known)}")
    enabled = block.get(c.INFERENCE_SPECULATIVE_ENABLED,
                        c.INFERENCE_SPECULATIVE_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_SPECULATIVE}."
            f"{c.INFERENCE_SPECULATIVE_ENABLED} must be a boolean, got "
            f"{enabled!r}")
    if not enabled:
        return None

    where = f"inference.{c.INFERENCE_SPECULATIVE}"
    k = as_int(block.get(c.INFERENCE_SPECULATIVE_NUM_DRAFT,
                         c.INFERENCE_SPECULATIVE_NUM_DRAFT_DEFAULT),
               f"{where}.{c.INFERENCE_SPECULATIVE_NUM_DRAFT}")
    if k < 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_SPECULATIVE_NUM_DRAFT} must be >= 1, "
            f"got {k}")

    quant = block.get(c.INFERENCE_SPECULATIVE_DRAFT_WEIGHT_QUANT,
                      c.INFERENCE_SPECULATIVE_DRAFT_WEIGHT_QUANT_DEFAULT)
    if quant is not None and quant not in c.QUANTIZATION_WEIGHTS_CHOICES:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_SPECULATIVE_DRAFT_WEIGHT_QUANT} must "
            f"be null or one of {list(c.QUANTIZATION_WEIGHTS_CHOICES)}, "
            f"got {quant!r}")

    return {"num_draft_tokens": k, "draft_weight_quant": quant}


def _parse_inference_disaggregation(block):
    """Validate the ``inference.disaggregation`` sub-block -> params
    dict. ALWAYS returns a dict (role "unified" when absent — today's
    single-engine behavior), so the engine reads one shape."""
    if block is None:
        block = {}
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_DISAGGREGATION} must be an object, "
            f"got {type(block).__name__}")
    known = {c.INFERENCE_DISAGG_ROLE, c.INFERENCE_DISAGG_POOL_ID,
             c.INFERENCE_DISAGG_HANDOFF_TIMEOUT}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference.{c.INFERENCE_DISAGGREGATION}' key(s) "
            f"{unknown}; valid keys: {sorted(known)}")
    where = f"inference.{c.INFERENCE_DISAGGREGATION}"

    role = block.get(c.INFERENCE_DISAGG_ROLE,
                     c.INFERENCE_DISAGG_ROLE_DEFAULT)
    if role not in c.INFERENCE_DISAGG_ROLE_CHOICES:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_DISAGG_ROLE} must be one of "
            f"{list(c.INFERENCE_DISAGG_ROLE_CHOICES)}, got {role!r}")

    pool_id = block.get(c.INFERENCE_DISAGG_POOL_ID,
                        c.INFERENCE_DISAGG_POOL_ID_DEFAULT)
    if pool_id is None:
        pool_id = f"{role}-0"
    if not isinstance(pool_id, str) or not pool_id or \
            any(ch in pool_id for ch in "/:"):
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_DISAGG_POOL_ID} must be a non-empty "
            f"string without '/' or ':' (it namespaces transport "
            f"keys), got {pool_id!r}")

    timeout = block.get(c.INFERENCE_DISAGG_HANDOFF_TIMEOUT,
                        c.INFERENCE_DISAGG_HANDOFF_TIMEOUT_DEFAULT)
    if not isinstance(timeout, (int, float)) or \
            isinstance(timeout, bool) or timeout <= 0:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_DISAGG_HANDOFF_TIMEOUT} must be a "
            f"number > 0 (seconds), got {timeout!r}")

    return {"role": role, "pool_id": pool_id,
            "handoff_timeout_s": float(timeout)}


def _parse_inference_router(block):
    """Validate the ``inference.router`` sub-block -> params dict, or
    None when absent (`ServeRouter` then runs on defaults)."""
    if block is None:
        return None
    if not isinstance(block, dict):
        raise DeepSpeedConfigError(
            f"inference.{c.INFERENCE_ROUTER} must be an object, got "
            f"{type(block).__name__}")
    known = {c.INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT,
             c.INFERENCE_ROUTER_POOL_UTIL_WEIGHT,
             c.INFERENCE_ROUTER_TTFT_WEIGHT,
             c.INFERENCE_ROUTER_SCALE_UP_UTIL}
    unknown = sorted(set(block) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'inference.{c.INFERENCE_ROUTER}' key(s) "
            f"{unknown}; valid keys: {sorted(known)}")
    where = f"inference.{c.INFERENCE_ROUTER}"

    out = {}
    for key, default in (
            (c.INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT,
             c.INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT_DEFAULT),
            (c.INFERENCE_ROUTER_POOL_UTIL_WEIGHT,
             c.INFERENCE_ROUTER_POOL_UTIL_WEIGHT_DEFAULT),
            (c.INFERENCE_ROUTER_TTFT_WEIGHT,
             c.INFERENCE_ROUTER_TTFT_WEIGHT_DEFAULT)):
        value = block.get(key, default)
        if not isinstance(value, (int, float)) or \
                isinstance(value, bool) or value < 0:
            raise DeepSpeedConfigError(
                f"{where}.{key} must be a number >= 0, got {value!r}")
        out[key] = float(value)

    util = block.get(c.INFERENCE_ROUTER_SCALE_UP_UTIL,
                     c.INFERENCE_ROUTER_SCALE_UP_UTIL_DEFAULT)
    if not isinstance(util, (int, float)) or isinstance(util, bool) or \
            not 0 < util <= 1:
        raise DeepSpeedConfigError(
            f"{where}.{c.INFERENCE_ROUTER_SCALE_UP_UTIL} must be a "
            f"number in (0, 1], got {util!r}")
    out[c.INFERENCE_ROUTER_SCALE_UP_UTIL] = float(util)
    return out


def parse_quantization_block(d):
    """Parse + validate the "quantization" block (docs/quantization.md):
    serving int8 weights, the delayed-scaling fp8/int8 FFN, and
    error-feedback compressed gradients. Module-level so the
    `InferenceEngine` can validate a raw config dict (it consumes
    ``weights``); the training engine consumes ``ffn`` and
    ``gradient_compression``. Same parse-time strictness as the
    "checkpoint" block.

    Returns {"weights": str|None, "ffn": dict|None,
    "gradient_compression": bool} or False when absent/disabled."""
    qz = d.get(c.QUANTIZATION)
    if qz is None:
        return False
    if not isinstance(qz, dict):
        raise DeepSpeedConfigError(
            f"'{c.QUANTIZATION}' must be an object, got "
            f"{type(qz).__name__}")
    known = {c.QUANTIZATION_ENABLED, c.QUANTIZATION_WEIGHTS,
             c.QUANTIZATION_FFN, c.QUANTIZATION_GRAD_COMPRESSION}
    unknown = sorted(set(qz) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown '{c.QUANTIZATION}' key(s) {unknown}; valid keys: "
            f"{sorted(known)}")
    enabled = qz.get(c.QUANTIZATION_ENABLED,
                     c.QUANTIZATION_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"{c.QUANTIZATION}.{c.QUANTIZATION_ENABLED} must be a "
            f"boolean, got {enabled!r}")
    if not enabled:
        return False

    weights = qz.get(c.QUANTIZATION_WEIGHTS,
                     c.QUANTIZATION_WEIGHTS_DEFAULT)
    if weights is not None and \
            weights not in c.QUANTIZATION_WEIGHTS_CHOICES:
        raise DeepSpeedConfigError(
            f"{c.QUANTIZATION}.{c.QUANTIZATION_WEIGHTS} must be null or "
            f"one of {list(c.QUANTIZATION_WEIGHTS_CHOICES)}, got "
            f"{weights!r}")

    ffn = qz.get(c.QUANTIZATION_FFN)
    if ffn is not None:
        if not isinstance(ffn, dict):
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_FFN} must be an "
                f"object, got {type(ffn).__name__}")
        fknown = {c.QUANTIZATION_FFN_RECIPE, c.QUANTIZATION_FFN_HISTORY,
                  c.QUANTIZATION_FFN_MARGIN}
        funknown = sorted(set(ffn) - fknown)
        if funknown:
            raise DeepSpeedConfigError(
                f"Unknown '{c.QUANTIZATION}.{c.QUANTIZATION_FFN}' "
                f"key(s) {funknown}; valid keys: {sorted(fknown)}")
        recipe = ffn.get(c.QUANTIZATION_FFN_RECIPE)
        if recipe not in c.QUANTIZATION_FFN_RECIPE_CHOICES:
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_FFN}."
                f"{c.QUANTIZATION_FFN_RECIPE} is required and must be "
                f"one of {list(c.QUANTIZATION_FFN_RECIPE_CHOICES)}, got "
                f"{recipe!r}")
        hist = as_int(ffn.get(c.QUANTIZATION_FFN_HISTORY,
                              c.QUANTIZATION_FFN_HISTORY_DEFAULT),
                      f"{c.QUANTIZATION}.{c.QUANTIZATION_FFN}."
                      f"{c.QUANTIZATION_FFN_HISTORY}")
        if hist < 1:
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_FFN}."
                f"{c.QUANTIZATION_FFN_HISTORY} must be >= 1, got {hist}")
        margin = ffn.get(c.QUANTIZATION_FFN_MARGIN,
                         c.QUANTIZATION_FFN_MARGIN_DEFAULT)
        if not isinstance(margin, (int, float)) or \
                isinstance(margin, bool) or margin <= 0:
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_FFN}."
                f"{c.QUANTIZATION_FFN_MARGIN} must be a number > 0, got "
                f"{margin!r}")
        ffn = {"recipe": recipe, "amax_history_len": hist,
               "margin": float(margin)}

    gc = qz.get(c.QUANTIZATION_GRAD_COMPRESSION)
    grad_compression = False
    grad_compression_packed = False
    if gc is not None:
        if not isinstance(gc, dict):
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_GRAD_COMPRESSION} "
                f"must be an object, got {type(gc).__name__}")
        gknown = {c.QUANTIZATION_GRAD_COMPRESSION_ENABLED,
                  c.QUANTIZATION_GRAD_COMPRESSION_PACKED}
        gunknown = sorted(set(gc) - gknown)
        if gunknown:
            raise DeepSpeedConfigError(
                f"Unknown '{c.QUANTIZATION}."
                f"{c.QUANTIZATION_GRAD_COMPRESSION}' key(s) {gunknown}; "
                f"valid keys: {sorted(gknown)}")
        grad_compression = gc.get(
            c.QUANTIZATION_GRAD_COMPRESSION_ENABLED,
            c.QUANTIZATION_GRAD_COMPRESSION_ENABLED_DEFAULT)
        if not isinstance(grad_compression, bool):
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_GRAD_COMPRESSION}."
                f"{c.QUANTIZATION_GRAD_COMPRESSION_ENABLED} must be a "
                f"boolean, got {grad_compression!r}")
        packed = gc.get(c.QUANTIZATION_GRAD_COMPRESSION_PACKED,
                        c.QUANTIZATION_GRAD_COMPRESSION_PACKED_DEFAULT)
        if not isinstance(packed, bool):
            raise DeepSpeedConfigError(
                f"{c.QUANTIZATION}.{c.QUANTIZATION_GRAD_COMPRESSION}."
                f"{c.QUANTIZATION_GRAD_COMPRESSION_PACKED} must be a "
                f"boolean, got {packed!r}")
        grad_compression_packed = grad_compression and packed

    return {"weights": weights, "ffn": ffn,
            "gradient_compression": grad_compression,
            "gradient_compression_packed": grad_compression_packed}


class DeepSpeedConfigWriter:
    """In-memory config builder that serializes to the JSON schema
    (reference `config.py:519`)."""

    def __init__(self, data=None):
        self.data = {} if data is None else data

    def add_config(self, key, value):
        self.data[key] = value

    def load_config(self, filename):
        import json
        with open(filename, "r") as f:
            self.data = json.load(f)
        return self.data

    def write_config(self, filename):
        import json
        with open(filename, "w") as f:
            json.dump(self.data, f, indent=4)


class DeepSpeedConfig:
    """Parsed, validated DeepSpeed config.

    Accepts a path to a JSON file or an already-loaded dict. ``mesh_shape``
    carries the (dp, mp, pp) decomposition so the batch triad resolves
    against the *data-parallel* world size, mirroring the mpu-aware logic in
    the reference (`config.py:550-560`).
    """

    def __init__(self, json_file_or_dict, mpu=None, param_dict=None,
                 world_size=None):
        if param_dict is not None:
            self._param_dict = dict(param_dict)
        elif isinstance(json_file_or_dict, dict):
            self._param_dict = dict(json_file_or_dict)
        else:
            self._param_dict = load_config_json(json_file_or_dict)

        if world_size is not None:
            self.world_size = int(world_size)
        elif mpu is not None:
            self.world_size = int(mpu.get_data_parallel_world_size())
        else:
            self.world_size = _default_dp_world_size()

        # Elastic jobs overwrite the batch triad from the solver.
        self.elasticity_enabled = elasticity_enabled(self._param_dict)
        if self.elasticity_enabled:
            (final_batch_size, valid_gpus,
             micro_batch_size) = compute_elastic_config(
                 ds_config=self._param_dict,
                 target_deepspeed_version=__version__,
                 world_size=self.world_size)
            elastic_dict = self._param_dict[ELASTICITY]
            ensure_immutable_elastic_config(elastic_dict)
            self.elastic_model_parallel_size = 1
            ignore_non_elastic = elastic_dict.get(
                IGNORE_NON_ELASTIC_BATCH_INFO,
                IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
            if not ignore_non_elastic:
                batch_params = (c.TRAIN_BATCH_SIZE,
                                c.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                                c.GRADIENT_ACCUMULATION_STEPS)
                if any(k in self._param_dict for k in batch_params):
                    raise DeepSpeedConfigError(
                        "One or more batch-related parameters were found in "
                        "your config json. These are superseded by the "
                        "elasticity config; remove them or set "
                        f"'{IGNORE_NON_ELASTIC_BATCH_INFO}': true")
            gas = final_batch_size // (micro_batch_size * self.world_size)
            self._param_dict[c.TRAIN_BATCH_SIZE] = final_batch_size
            self._param_dict[c.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = \
                micro_batch_size
            self._param_dict[c.GRADIENT_ACCUMULATION_STEPS] = gas
            self.elastic_valid_gpus = valid_gpus

        # Profile-guided schedule planner: resolve and overlay the
        # configured plan BEFORE the blocks parse — plan-provided keys
        # then pass the exact same strict validation a hand-written
        # config would, and user-set keys always win the merge.
        self.planner_config = parse_planner_block(self._param_dict)
        if self.planner_config is not None:
            from ..planner.apply import overlay_plan
            (self.planner_plan_fingerprint,
             self.planner_applied_keys) = overlay_plan(
                self._param_dict, self.planner_config)
        else:
            self.planner_plan_fingerprint = None
            self.planner_applied_keys = []

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # -- parsing -----------------------------------------------------------

    def _initialize_params(self, d):
        self.train_batch_size = d.get(c.TRAIN_BATCH_SIZE,
                                      c.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = d.get(
            c.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            c.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = d.get(
            c.GRADIENT_ACCUMULATION_STEPS,
            c.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = as_int(
            d.get(c.STEPS_PER_PRINT, c.STEPS_PER_PRINT_DEFAULT),
            c.STEPS_PER_PRINT)
        self.dump_state = bool(d.get(c.DUMP_STATE, c.DUMP_STATE_DEFAULT))

        self.disable_allgather = bool(
            d.get(c.DISABLE_ALLGATHER, c.DISABLE_ALLGATHER_DEFAULT))
        self.gradient_predivide_factor = float(
            d.get(c.GRADIENT_PREDIVIDE_FACTOR,
                  c.GRADIENT_PREDIVIDE_FACTOR_DEFAULT))
        self.sparse_gradients_enabled = bool(
            d.get(c.SPARSE_GRADIENTS, c.SPARSE_GRADIENTS_DEFAULT))

        self.zero_config = DeepSpeedZeroConfig.from_dict(d)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_config.enabled

        # Parse validates knob types and the remat-policy name against
        # the registry (unknown names raise with the valid choices);
        # `number_checkpoints <= num_layers` is enforced model-side where
        # the layer count is known (models.gpt_neox.
        # apply_activation_checkpointing_config).
        self.activation_checkpointing_config = (
            DeepSpeedActivationCheckpointingConfig.from_dict(d))
        self.aio_config = DeepSpeedAIOConfig.from_dict(d)
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig.from_dict(d)

        # Mixed precision. "fp16" block carries both fp16 and bf16 (fork).
        fp16 = d.get(c.FP16) or {}
        self.fp16_enabled = bool(
            fp16.get(c.FP16_ENABLED, c.FP16_ENABLED_DEFAULT))
        self.precision = (resolve_precision(
            fp16.get(c.FP16_TYPE, c.FP16_TYPE_DEFAULT))
            if self.fp16_enabled else jnp.float32)
        self.bfloat16_enabled = self.precision == jnp.bfloat16
        self.loss_scale = fp16.get(c.FP16_LOSS_SCALE,
                                   c.FP16_LOSS_SCALE_DEFAULT)
        self.initial_dynamic_scale = 2 ** as_int(
            fp16.get(c.FP16_INITIAL_SCALE_POWER,
                     c.FP16_INITIAL_SCALE_POWER_DEFAULT),
            c.FP16_INITIAL_SCALE_POWER)
        self.dynamic_loss_scale_args = {
            c.FP16_INITIAL_SCALE_POWER: as_int(
                fp16.get(c.FP16_INITIAL_SCALE_POWER,
                         c.FP16_INITIAL_SCALE_POWER_DEFAULT),
                c.FP16_INITIAL_SCALE_POWER),
            c.FP16_LOSS_SCALE_WINDOW: as_int(
                fp16.get(c.FP16_LOSS_SCALE_WINDOW,
                         c.FP16_LOSS_SCALE_WINDOW_DEFAULT),
                c.FP16_LOSS_SCALE_WINDOW),
            c.FP16_HYSTERESIS: as_int(
                fp16.get(c.FP16_HYSTERESIS, c.FP16_HYSTERESIS_DEFAULT),
                c.FP16_HYSTERESIS),
            c.FP16_MIN_LOSS_SCALE: fp16.get(c.FP16_MIN_LOSS_SCALE,
                                            c.FP16_MIN_LOSS_SCALE_DEFAULT),
        } if self.fp16_enabled else None
        # bf16/fp32 never need loss scaling even when configured.
        self.loss_scaling_enabled = (self.fp16_enabled
                                     and needs_loss_scaling(self.precision))
        # Consecutive overflow-skipped steps tolerated at the
        # min_loss_scale floor before a loud error (0 = warn-only; see
        # fp16/loss_scaler.ScaleFloorWatch).
        self.min_scale_patience = as_int(
            fp16.get(c.FP16_MIN_SCALE_PATIENCE,
                     c.FP16_MIN_SCALE_PATIENCE_DEFAULT),
            f"fp16.{c.FP16_MIN_SCALE_PATIENCE}")
        if self.min_scale_patience < 0:
            raise DeepSpeedConfigError(
                f"fp16.{c.FP16_MIN_SCALE_PATIENCE} must be >= 0 "
                f"(0 = warn-only), got {self.min_scale_patience}")
        # Later-DeepSpeed key (forward-port): drop the separate fp32
        # master copy — optimizer math upcasts from the compute-dtype
        # params and stores back. Halves per-param bytes-at-rest; the
        # memory knob that puts GPT2-XL's on-chip rung inside 16 GB.
        self.fp16_master_weights_and_grads = bool(
            fp16.get("fp16_master_weights_and_grads", False))

        amp = d.get(c.AMP) or {}
        self.amp_enabled = bool(amp.get(c.AMP_ENABLED, c.AMP_ENABLED_DEFAULT))
        self.amp_params = {k: v for k, v in amp.items() if k != c.AMP_ENABLED}

        self.gradient_clipping = float(
            d.get(c.GRADIENT_CLIPPING, c.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients = bool(
            d.get(c.PRESCALE_GRADIENTS, c.PRESCALE_GRADIENTS_DEFAULT))
        # bf16 grads default to fp32-upcast reductions (fork: engine.py:613-620).
        fp32_allreduce_default = (c.FP32_ALLREDUCE_DEFAULT_BF16
                                  if self.bfloat16_enabled else
                                  c.FP32_ALLREDUCE_DEFAULT)
        self.fp32_allreduce = bool(
            d.get(c.FP32_ALLREDUCE, fp32_allreduce_default))

        optimizer = d.get(c.OPTIMIZER)
        if optimizer is not None:
            self.optimizer_name = str(optimizer.get(c.TYPE, "")).lower() or None
            self.optimizer_params = dict(optimizer.get(c.OPTIMIZER_PARAMS, {}))
            self.optimizer_legacy_fusion = bool(
                optimizer.get(c.LEGACY_FUSION, c.LEGACY_FUSION_DEFAULT))
        else:
            self.optimizer_name = c.OPTIMIZER_TYPE_DEFAULT
            self.optimizer_params = None
            self.optimizer_legacy_fusion = c.LEGACY_FUSION_DEFAULT
        self.zero_allow_untested_optimizer = bool(
            d.get(c.ZERO_ALLOW_UNTESTED_OPTIMIZER,
                  c.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT))

        scheduler = d.get(c.SCHEDULER)
        if scheduler is not None:
            self.scheduler_name = scheduler.get(c.TYPE)
            self.scheduler_params = dict(scheduler.get(c.SCHEDULER_PARAMS, {}))
        else:
            self.scheduler_name = c.SCHEDULER_TYPE_DEFAULT
            self.scheduler_params = None

        self.wall_clock_breakdown = bool(
            d.get(c.WALL_CLOCK_BREAKDOWN, c.WALL_CLOCK_BREAKDOWN_DEFAULT))
        self.memory_breakdown = bool(
            d.get(c.MEMORY_BREAKDOWN, c.MEMORY_BREAKDOWN_DEFAULT))

        tb = d.get(c.TENSORBOARD) or {}
        self.tensorboard_enabled = bool(
            tb.get(c.TENSORBOARD_ENABLED, c.TENSORBOARD_ENABLED_DEFAULT))
        self.tensorboard_output_path = tb.get(
            c.TENSORBOARD_OUTPUT_PATH, c.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.tensorboard_job_name = tb.get(c.TENSORBOARD_JOB_NAME,
                                           c.TENSORBOARD_JOB_NAME_DEFAULT)
        self._parse_monitor_block(d)

        self.sparse_attention = _parse_sparse_attention(d)

        pld = d.get(c.PROGRESSIVE_LAYER_DROP) or {}
        self.pld_enabled = bool(pld.get(c.PLD_ENABLED, c.PLD_ENABLED_DEFAULT))
        self.pld_params = {
            c.PLD_THETA: pld.get(c.PLD_THETA, c.PLD_THETA_DEFAULT),
            c.PLD_GAMMA: pld.get(c.PLD_GAMMA, c.PLD_GAMMA_DEFAULT),
        } if self.pld_enabled else False

        # Config-drivable MoE / sequence parallelism (the engine hands
        # these to the model family via `apply_ds_config`; no library
        # imports needed in user code).
        self._parse_moe_block(d)
        sp = d.get("sequence_parallel") or {}
        self.sequence_parallel_enabled = bool(sp.get("enabled", False))
        self.sequence_parallel_params = {
            "mode": str(sp.get("mode", "ring")),
            "axis": str(sp.get("axis", "sp")),
        } if self.sequence_parallel_enabled else False

        bs_sched = d.get(c.BATCH_SIZE_SCHEDULE) or {}
        self.batch_size_schedule_enabled = bool(
            bs_sched.get(c.BS_SCHEDULE_ENABLED, c.BS_SCHEDULE_ENABLED_DEFAULT))
        self.batch_size_schedule_params = dict(
            bs_sched.get(c.BS_SCHEDULE_PARAMS, {}))

        self._parse_checkpoint_block(d)
        self._parse_training_health_block(d)
        self._parse_telemetry_block(d)
        self._parse_packing_block(d)
        self._parse_pipeline_block(d)

        # Elastic resilience sub-blocks ("elasticity": {"heartbeat",
        # "supervisor"}) — validated at the same parse-time strictness
        # as the blocks above (elasticity/config.py), independent of the
        # batch-solver `enabled` flag: a job can run peer heartbeats and
        # supervised restarts without elastic batch arithmetic. The
        # supervisor block itself is consumed by the LAUNCHER; parsing
        # it here means a typo'd restart budget still fails at startup.
        from ..elasticity import parse_resilience_config
        self.elasticity_resilience = parse_resilience_config(d)

        # Serving engine (deeperspeed_tpu/inference); module-level parse
        # so InferenceEngine validates raw dicts identically.
        self.inference_params = parse_inference_block(d)
        self.inference_enabled = bool(self.inference_params)

        # Online-RL driver (deeperspeed_tpu/rl); module-level parse so
        # RLDriver validates raw dicts identically.
        self.rl_params = parse_rl_block(d)
        self.rl_enabled = bool(self.rl_params)

        # Low-precision hot paths (docs/quantization.md); module-level
        # parse so InferenceEngine validates raw dicts identically.
        self.quantization_config = parse_quantization_block(d) or None

        # Multi-slice composition over DCN (docs/multislice.md) — parsed
        # after pipeline + quantization, whose blocks it composes with.
        self._parse_multislice_block(d)

        # Fork additions: gradient storage for debugging.
        self.store_gradients = bool(
            d.get(c.STORE_GRADIENTS, c.STORE_GRADIENTS_DEFAULT))
        self.store_gradients_cpu = bool(
            d.get(c.STORE_GRADIENTS_CPU, c.STORE_GRADIENTS_CPU_DEFAULT))

        self.vocabulary_size = d.get(c.VOCABULARY_SIZE,
                                     c.VOCABULARY_SIZE_DEFAULT)

    def _parse_pipeline_block(self, d):
        """Parse + validate the "pipeline" block (config-driven 1F1B
        schedule over a ``pipe`` mesh axis) at checkpoint-block
        strictness. Unsupported combos reject HERE, at parse: a pipeline
        block silently ignored next to an offload tier or ZeRO >= 2
        would train unscheduled while the user believes it pipelines."""
        pipe = d.get(c.PIPELINE)
        if pipe is None:
            self.pipeline_config = None
            return
        if not isinstance(pipe, dict):
            raise DeepSpeedConfigError(
                f"'{c.PIPELINE}' must be a dict, got {pipe!r}")
        known = {c.PIPELINE_STAGES, c.PIPELINE_MICRO_BATCHES,
                 c.PIPELINE_COMM_OVERLAP}
        unknown = sorted(set(pipe) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'pipeline' key(s) {unknown}; valid keys: "
                f"{sorted(known)}")
        if c.PIPELINE_STAGES not in pipe:
            raise DeepSpeedConfigError(
                f"pipeline.{c.PIPELINE_STAGES} is required (the number "
                f"of pipeline stages, >= 2)")
        stages = as_int(pipe[c.PIPELINE_STAGES],
                        f"pipeline.{c.PIPELINE_STAGES}")
        if stages < 2:
            raise DeepSpeedConfigError(
                f"pipeline.{c.PIPELINE_STAGES} must be >= 2 (a 1-stage "
                f"pipeline is the plain engine — drop the block), got "
                f"{stages}")
        micro = pipe.get(c.PIPELINE_MICRO_BATCHES)
        if micro is not None:
            micro = as_int(micro, f"pipeline.{c.PIPELINE_MICRO_BATCHES}")
            if micro < 1:
                raise DeepSpeedConfigError(
                    f"pipeline.{c.PIPELINE_MICRO_BATCHES} must be >= 1, "
                    f"got {micro}")
        overlap = pipe.get(c.PIPELINE_COMM_OVERLAP,
                           c.PIPELINE_COMM_OVERLAP_DEFAULT)
        if not isinstance(overlap, bool):
            raise DeepSpeedConfigError(
                f"pipeline.{c.PIPELINE_COMM_OVERLAP} must be a boolean, "
                f"got {overlap!r}")

        # -- unsupported combos: reject loudly at parse ------------------
        if self.zero_optimization_stage >= 2:
            raise DeepSpeedConfigError(
                f"pipeline parallelism composes with ZeRO stage <= 1 "
                f"only (the reference makes the same restriction): "
                f"grads/params are stage-local, not dp-flat. Got stage "
                f"{self.zero_optimization_stage}; for dp-axis param "
                f"sharding use zero_optimization.schedule.mode="
                f"\"explicit\" without the pipeline block")
        if self.zero_config.offload_optimizer is not None or \
                self.zero_config.offload_param is not None:
            tier = ("streamed-NVMe" if self.zero_config.nvme_offload
                    else "host-offload")
            raise DeepSpeedConfigError(
                f"pipeline parallelism is unsupported with the {tier} "
                f"offload tier: the offload paths accumulate per-micro-"
                f"batch grads outside the fused 1F1B program (the run "
                f"would silently train unscheduled)")
        if self.moe_enabled:
            raise DeepSpeedConfigError(
                "pipeline + moe is unsupported: the expert aux loss is "
                "not threaded through the inter-stage buffers")
        if self.sequence_parallel_enabled:
            raise DeepSpeedConfigError(
                "pipeline + sequence_parallel is unsupported: the SP "
                "ring owns its own mesh axis and full-sequence layouts")
        if getattr(self, "packing_params", None):
            raise DeepSpeedConfigError(
                "pipeline + packing is unsupported: segment_ids are not "
                "threaded through the inter-stage buffers")
        if self.sparse_attention:
            raise DeepSpeedConfigError(
                "pipeline + sparse_attention is unsupported: the "
                "pipelined stage body runs the dense block")
        if self.pld_enabled:
            raise DeepSpeedConfigError(
                "pipeline + progressive_layer_drop is unsupported: "
                "theta is not threaded through the 1F1B program")
        self.pipeline_config = {
            "stages": stages,
            "micro_batches": micro,
            "comm_overlap": overlap,
        }

    def _parse_multislice_block(self, d):
        """Parse + validate the "multislice" block (docs/multislice.md):
        the mesh is partitioned into named slices joined by a ~10x
        slower DCN fabric, and the slice becomes the unit of staleness
        escalation for the elastic layer. Checkpoint-block strictness —
        a silently inert multislice block would run every stage
        boundary over "DCN" without the wire policy the user asked for.

        Must run AFTER `_parse_pipeline_block` and
        `parse_quantization_block`: axis="pipe" partitions the pipeline
        stages, axis="data" routes the cross-slice dp reduction over
        the EF compressed wire (requires gradient_compression)."""
        ms = d.get(c.MULTISLICE)
        if ms is None:
            self.multislice_config = None
            return
        if not isinstance(ms, dict):
            raise DeepSpeedConfigError(
                f"'{c.MULTISLICE}' must be a dict, got {ms!r}")
        known = {c.MULTISLICE_SLICES, c.MULTISLICE_AXIS,
                 c.MULTISLICE_NAMES, c.MULTISLICE_SLICE_PEERS,
                 c.MULTISLICE_DCN, c.MULTISLICE_SURVIVE}
        unknown = sorted(set(ms) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown '{c.MULTISLICE}' key(s) {unknown}; valid "
                f"keys: {sorted(known)}")
        if c.MULTISLICE_SLICES not in ms:
            raise DeepSpeedConfigError(
                f"{c.MULTISLICE}.{c.MULTISLICE_SLICES} is required "
                f"(the number of slices, >= 2)")
        slices = as_int(ms[c.MULTISLICE_SLICES],
                        f"{c.MULTISLICE}.{c.MULTISLICE_SLICES}")
        if slices < 2:
            raise DeepSpeedConfigError(
                f"{c.MULTISLICE}.{c.MULTISLICE_SLICES} must be >= 2 "
                f"(a single slice has no DCN boundary — drop the "
                f"block), got {slices}")

        axis = ms.get(c.MULTISLICE_AXIS, c.MULTISLICE_AXIS_DEFAULT)
        if axis not in c.MULTISLICE_AXIS_CHOICES:
            raise DeepSpeedConfigError(
                f"{c.MULTISLICE}.{c.MULTISLICE_AXIS} must be one of "
                f"{list(c.MULTISLICE_AXIS_CHOICES)}, got {axis!r}")

        names = ms.get(c.MULTISLICE_NAMES)
        if names is None:
            names = [f"slice{i}" for i in range(slices)]
        else:
            if not isinstance(names, list) or \
                    not all(isinstance(n, str) and n for n in names):
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_NAMES} must be a "
                    f"list of non-empty strings, got {names!r}")
            if len(names) != slices:
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_NAMES} must name "
                    f"every slice (len {slices}), got {len(names)}")
            if len(set(names)) != len(names):
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_NAMES} must be "
                    f"unique, got {names!r}")

        slice_peers = ms.get(c.MULTISLICE_SLICE_PEERS)
        if slice_peers is not None:
            if not isinstance(slice_peers, dict):
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_SLICE_PEERS} must "
                    f"be a dict of slice name -> [peer names], got "
                    f"{slice_peers!r}")
            bad = sorted(set(slice_peers) - set(names))
            if bad:
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_SLICE_PEERS} names "
                    f"unknown slice(s) {bad}; slices: {names}")
            seen = {}
            for sname, peers in slice_peers.items():
                if not isinstance(peers, list) or not peers or \
                        not all(isinstance(p, str) and p for p in peers):
                    raise DeepSpeedConfigError(
                        f"{c.MULTISLICE}.{c.MULTISLICE_SLICE_PEERS}."
                        f"{sname} must be a non-empty list of peer "
                        f"names, got {peers!r}")
                for p in peers:
                    if p in seen:
                        raise DeepSpeedConfigError(
                            f"peer {p!r} is mapped to both slice "
                            f"{seen[p]!r} and {sname!r} — a host lives "
                            f"in exactly one slice")
                    seen[p] = sname
            slice_peers = {s: list(p) for s, p in slice_peers.items()}

        dcn = ms.get(c.MULTISLICE_DCN) or {}
        if not isinstance(dcn, dict):
            raise DeepSpeedConfigError(
                f"{c.MULTISLICE}.{c.MULTISLICE_DCN} must be a dict, "
                f"got {dcn!r}")
        dknown = {c.MULTISLICE_DCN_FP32_COMM, c.MULTISLICE_DCN_PACKED_WIRE,
                  c.MULTISLICE_DCN_COMPRESS}
        dunknown = sorted(set(dcn) - dknown)
        if dunknown:
            raise DeepSpeedConfigError(
                f"Unknown '{c.MULTISLICE}.{c.MULTISLICE_DCN}' key(s) "
                f"{dunknown}; valid keys: {sorted(dknown)}")
        dcn_out = {}
        for key, default in (
                (c.MULTISLICE_DCN_FP32_COMM,
                 c.MULTISLICE_DCN_FP32_COMM_DEFAULT),
                (c.MULTISLICE_DCN_PACKED_WIRE,
                 c.MULTISLICE_DCN_PACKED_WIRE_DEFAULT),
                (c.MULTISLICE_DCN_COMPRESS,
                 c.MULTISLICE_DCN_COMPRESS_DEFAULT)):
            val = dcn.get(key, default)
            if not isinstance(val, bool):
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_DCN}.{key} must be "
                    f"a boolean, got {val!r}")
            dcn_out[key] = val

        survive = ms.get(c.MULTISLICE_SURVIVE, c.MULTISLICE_SURVIVE_DEFAULT)
        if not isinstance(survive, bool):
            raise DeepSpeedConfigError(
                f"{c.MULTISLICE}.{c.MULTISLICE_SURVIVE} must be a "
                f"boolean, got {survive!r}")

        # -- composition: the slice cut must land on a real axis ---------
        if axis == "pipe":
            if self.pipeline_config is None:
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE} axis \"pipe\" needs the pipeline "
                    f"block: slices partition the 1F1B stages "
                    f"(docs/multislice.md)")
            stages = self.pipeline_config["stages"]
            if stages % slices != 0:
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_SLICES} ({slices}) "
                    f"must divide pipeline.stages ({stages}): slices "
                    f"hold contiguous equal-size stage spans")
            if survive and stages // slices < 2:
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE}.{c.MULTISLICE_SURVIVE} needs >= 2 "
                    f"stages per slice: losing a slice must leave a "
                    f">= 2-stage pipeline (the checkpoint layout guard "
                    f"rejects a pipeline -> sequential re-partition), "
                    f"got {stages}//{slices} = {stages // slices}")
        else:  # axis == "data"
            if self.pipeline_config is not None:
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE} axis \"data\" + the pipeline "
                    f"block is unsupported (pipeline dp reduction is "
                    f"stage-local); use axis \"pipe\"")
            if dcn_out[c.MULTISLICE_DCN_COMPRESS] and not (
                    self.quantization_config
                    and self.quantization_config["gradient_compression"]):
                raise DeepSpeedConfigError(
                    f"{c.MULTISLICE} axis \"data\" with "
                    f"{c.MULTISLICE_DCN}.{c.MULTISLICE_DCN_COMPRESS} "
                    f"needs quantization.gradient_compression: only "
                    f"the EF sign-compressed wire is DCN-rated for the "
                    f"cross-slice dp reduction")

        self.multislice_config = {
            "slices": slices,
            "axis": axis,
            "names": names,
            "slice_peers": slice_peers,
            "dcn": dcn_out,
            "survive_slice_loss": survive,
        }

    def _parse_moe_block(self, d):
        """Parse + validate the "moe" block with the same parse-time
        strictness as the "checkpoint"/"training_health" blocks: a
        mistyped key or out-of-range knob must fail at startup, not
        silently train a dense (or mis-routed) model."""
        moe = d.get(c.MOE) or {}
        known = {c.MOE_ENABLED, c.MOE_NUM_EXPERTS, c.MOE_TOP_K,
                 c.MOE_CAPACITY_FACTOR, c.MOE_JITTER_EPS,
                 c.MOE_AUX_LOSS_COEF, c.MOE_NUM_GROUPS, c.MOE_DISPATCH,
                 c.MOE_A2A_OVERLAP_CHUNKS, c.MOE_RENORM_KEPT_CHOICES,
                 c.MOE_OBSERVABILITY}
        unknown = sorted(set(moe) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'moe' key(s) {unknown}; valid keys: "
                f"{sorted(known)}")

        self.moe_enabled = bool(moe.get(c.MOE_ENABLED,
                                        moe.get(c.MOE_NUM_EXPERTS, 0)))
        if not self.moe_enabled:
            self.moe_params = False
            return

        num_experts = as_int(moe.get(c.MOE_NUM_EXPERTS, 0),
                             f"moe.{c.MOE_NUM_EXPERTS}")
        if num_experts <= 0:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_NUM_EXPERTS} must be a positive int, got "
                f"{moe.get(c.MOE_NUM_EXPERTS)!r}")
        top_k = as_int(moe.get(c.MOE_TOP_K, c.MOE_TOP_K_DEFAULT),
                       f"moe.{c.MOE_TOP_K}")
        if top_k not in c.MOE_TOP_K_CHOICES:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_TOP_K} must be one of "
                f"{list(c.MOE_TOP_K_CHOICES)} (1 = Switch, 2 = GShard), "
                f"got {top_k}")
        def as_float(key, default):
            try:
                return float(moe.get(key, default))
            except (TypeError, ValueError):
                raise DeepSpeedConfigError(
                    f"moe.{key} must be a number, got {moe.get(key)!r}")

        capacity_factor = as_float(c.MOE_CAPACITY_FACTOR,
                                   c.MOE_CAPACITY_FACTOR_DEFAULT)
        if not capacity_factor > 0:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_CAPACITY_FACTOR} must be > 0, got "
                f"{capacity_factor}")
        jitter_eps = as_float(c.MOE_JITTER_EPS, c.MOE_JITTER_EPS_DEFAULT)
        if jitter_eps < 0:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_JITTER_EPS} must be >= 0, got {jitter_eps}")
        aux_loss_coef = as_float(c.MOE_AUX_LOSS_COEF,
                                 c.MOE_AUX_LOSS_COEF_DEFAULT)
        if aux_loss_coef < 0:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_AUX_LOSS_COEF} must be >= 0 (a negative "
                f"coefficient actively unbalances experts), got "
                f"{aux_loss_coef}")
        num_groups = as_int(moe.get(c.MOE_NUM_GROUPS,
                                    c.MOE_NUM_GROUPS_DEFAULT),
                            f"moe.{c.MOE_NUM_GROUPS}")
        if num_groups < 0:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_NUM_GROUPS} must be >= 0 (0 = auto), got "
                f"{num_groups}")
        dispatch = str(moe.get(c.MOE_DISPATCH, c.MOE_DISPATCH_DEFAULT))
        if dispatch not in c.MOE_DISPATCH_MODES:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_DISPATCH} must be one of "
                f"{list(c.MOE_DISPATCH_MODES)}, got {dispatch!r}")
        a2a_chunks = as_int(moe.get(c.MOE_A2A_OVERLAP_CHUNKS,
                                    c.MOE_A2A_OVERLAP_CHUNKS_DEFAULT),
                            f"moe.{c.MOE_A2A_OVERLAP_CHUNKS}")
        if a2a_chunks < 1:
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_A2A_OVERLAP_CHUNKS} must be >= 1, got "
                f"{a2a_chunks}")
        renorm = moe.get(c.MOE_RENORM_KEPT_CHOICES,
                         c.MOE_RENORM_KEPT_CHOICES_DEFAULT)
        if not isinstance(renorm, bool):
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_RENORM_KEPT_CHOICES} must be a boolean, "
                f"got {renorm!r}")
        observability = moe.get(c.MOE_OBSERVABILITY,
                                c.MOE_OBSERVABILITY_DEFAULT)
        if not isinstance(observability, bool):
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_OBSERVABILITY} must be a boolean, got "
                f"{observability!r}")
        if observability and dispatch != "sort":
            raise DeepSpeedConfigError(
                f"moe.{c.MOE_OBSERVABILITY} requires moe.dispatch="
                f"\"sort\": the expert-load / capacity-drop statistics "
                f"come from the sort engine's position-in-expert "
                f"bookkeeping (got dispatch={dispatch!r})")

        self.moe_params = {
            "num_experts": num_experts,
            "top_k": top_k,
            "capacity_factor": capacity_factor,
            "jitter_eps": jitter_eps,
            "aux_loss_coef": aux_loss_coef,
            "num_groups": num_groups,
            "dispatch": dispatch,
            "a2a_overlap_chunks": a2a_chunks,
            "renorm_kept_choices": renorm,
            "observability": observability,
        }

    def _parse_checkpoint_block(self, d):
        """Parse + validate the "checkpoint" block: tag validation
        (reference `config.py:804-812`) plus the fork's fault-tolerant
        async-save knobs (checkpoint/async_manager.py). Everything is
        validated at parse time — a mistyped retention knob must fail at
        startup, not at the first (possibly hours-away) save."""
        ckpt = d.get(c.CHECKPOINT) or {}
        known = {c.CHECKPOINT_TAG_VALIDATION, c.CHECKPOINT_SAVE_DIR,
                 c.CHECKPOINT_ASYNC_SAVE, c.CHECKPOINT_SAVE_INTERVAL,
                 c.CHECKPOINT_KEEP_LAST_N, c.CHECKPOINT_KEEP_EVERY_N_STEPS,
                 c.CHECKPOINT_SAVE_ON_PREEMPTION}
        unknown = sorted(set(ckpt) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'checkpoint' key(s) {unknown}; valid keys: "
                f"{sorted(known)}")

        self.checkpoint_tag_validation_mode = str(
            ckpt.get(c.CHECKPOINT_TAG_VALIDATION,
                     c.CHECKPOINT_TAG_VALIDATION_DEFAULT)).upper()
        if self.checkpoint_tag_validation_mode not in \
                c.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(
                f"checkpoint.{c.CHECKPOINT_TAG_VALIDATION} must be one of "
                f"{c.CHECKPOINT_TAG_VALIDATION_MODES}, got "
                f"{self.checkpoint_tag_validation_mode!r}")
        self.checkpoint_tag_validation_enabled = (
            self.checkpoint_tag_validation_mode != c.ValidationMode.IGNORE)
        self.checkpoint_tag_validation_fail = (
            self.checkpoint_tag_validation_mode == c.ValidationMode.FAIL)

        save_dir = ckpt.get(c.CHECKPOINT_SAVE_DIR,
                            c.CHECKPOINT_SAVE_DIR_DEFAULT)
        if save_dir is not None and not isinstance(save_dir, str):
            raise DeepSpeedConfigError(
                f"checkpoint.{c.CHECKPOINT_SAVE_DIR} must be a string "
                f"path, got {save_dir!r}")
        for key, default in ((c.CHECKPOINT_ASYNC_SAVE,
                              c.CHECKPOINT_ASYNC_SAVE_DEFAULT),
                             (c.CHECKPOINT_SAVE_ON_PREEMPTION,
                              c.CHECKPOINT_SAVE_ON_PREEMPTION_DEFAULT)):
            if not isinstance(ckpt.get(key, default), bool):
                raise DeepSpeedConfigError(
                    f"checkpoint.{key} must be a boolean, got "
                    f"{ckpt.get(key)!r}")
        ints = {}
        for key, default in ((c.CHECKPOINT_SAVE_INTERVAL,
                              c.CHECKPOINT_SAVE_INTERVAL_DEFAULT),
                             (c.CHECKPOINT_KEEP_LAST_N,
                              c.CHECKPOINT_KEEP_LAST_N_DEFAULT),
                             (c.CHECKPOINT_KEEP_EVERY_N_STEPS,
                              c.CHECKPOINT_KEEP_EVERY_N_STEPS_DEFAULT)):
            value = as_int(ckpt.get(key, default), f"checkpoint.{key}")
            if value < 0:
                raise DeepSpeedConfigError(
                    f"checkpoint.{key} must be >= 0 (0 disables), got "
                    f"{value}")
            ints[key] = value
        save_on_preemption = ckpt.get(c.CHECKPOINT_SAVE_ON_PREEMPTION,
                                      c.CHECKPOINT_SAVE_ON_PREEMPTION_DEFAULT)
        if save_dir is None and (ints[c.CHECKPOINT_SAVE_INTERVAL]
                                 or save_on_preemption):
            raise DeepSpeedConfigError(
                f"checkpoint.{c.CHECKPOINT_SAVE_DIR} is required when "
                f"{c.CHECKPOINT_SAVE_INTERVAL} or "
                f"{c.CHECKPOINT_SAVE_ON_PREEMPTION} is set (auto/emergency "
                "saves need somewhere to write)")
        self.checkpoint_config = {
            "save_dir": save_dir,
            "async_save": ckpt.get(c.CHECKPOINT_ASYNC_SAVE,
                                   c.CHECKPOINT_ASYNC_SAVE_DEFAULT),
            "save_interval_steps": ints[c.CHECKPOINT_SAVE_INTERVAL],
            "keep_last_n": ints[c.CHECKPOINT_KEEP_LAST_N],
            "keep_every_n_steps": ints[c.CHECKPOINT_KEEP_EVERY_N_STEPS],
            "save_on_preemption": save_on_preemption,
        }

    def _parse_training_health_block(self, d):
        """Parse + validate the "training_health" block (runtime/
        sentinel.py + runtime/fault_injection.py). Same parse-time
        strictness as the "checkpoint" block: a mistyped threshold or
        policy must fail at startup, not at the first (hours-away)
        anomaly. Runs AFTER _parse_checkpoint_block — the rollback policy
        cross-validates against checkpoint.save_dir."""
        th = d.get(c.TRAINING_HEALTH) or {}
        known = {c.TRAINING_HEALTH_ENABLED, c.TRAINING_HEALTH_POLICY,
                 c.TRAINING_HEALTH_LOSS_ZSCORE,
                 c.TRAINING_HEALTH_GRAD_NORM_ZSCORE,
                 c.TRAINING_HEALTH_EMA_BETA,
                 c.TRAINING_HEALTH_WARMUP_STEPS,
                 c.TRAINING_HEALTH_ROLLBACK_AFTER,
                 c.TRAINING_HEALTH_ABORT_AFTER,
                 c.TRAINING_HEALTH_MAX_ROLLBACKS,
                 c.TRAINING_HEALTH_HANG_TIMEOUT,
                 c.TRAINING_HEALTH_FAULT_INJECTION}
        unknown = sorted(set(th) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'training_health' key(s) {unknown}; valid "
                f"keys: {sorted(known)}")

        enabled = th.get(c.TRAINING_HEALTH_ENABLED,
                         c.TRAINING_HEALTH_ENABLED_DEFAULT)
        if not isinstance(enabled, bool):
            raise DeepSpeedConfigError(
                f"training_health.{c.TRAINING_HEALTH_ENABLED} must be a "
                f"boolean, got {enabled!r}")

        from .sentinel import POLICIES
        policy = th.get(c.TRAINING_HEALTH_POLICY,
                        c.TRAINING_HEALTH_POLICY_DEFAULT)
        if policy not in POLICIES:
            raise DeepSpeedConfigError(
                f"training_health.{c.TRAINING_HEALTH_POLICY} must be one "
                f"of {list(POLICIES)}, got {policy!r}")

        floats = {}
        for key, default, lo, hi in (
                (c.TRAINING_HEALTH_LOSS_ZSCORE,
                 c.TRAINING_HEALTH_LOSS_ZSCORE_DEFAULT, 0.0, None),
                (c.TRAINING_HEALTH_GRAD_NORM_ZSCORE,
                 c.TRAINING_HEALTH_GRAD_NORM_ZSCORE_DEFAULT, 0.0, None),
                (c.TRAINING_HEALTH_EMA_BETA,
                 c.TRAINING_HEALTH_EMA_BETA_DEFAULT, 0.0, 1.0),
                (c.TRAINING_HEALTH_HANG_TIMEOUT,
                 c.TRAINING_HEALTH_HANG_TIMEOUT_DEFAULT, 0.0, None)):
            value = th.get(key, default)
            if not isinstance(value, (int, float)) or \
                    isinstance(value, bool):
                raise DeepSpeedConfigError(
                    f"training_health.{key} must be a number, got "
                    f"{value!r}")
            value = float(value)
            if value < lo or (hi is not None and value >= hi):
                bound = f">= {lo}" if hi is None else f"in [{lo}, {hi})"
                raise DeepSpeedConfigError(
                    f"training_health.{key} must be {bound}, got {value}")
            floats[key] = value

        ints = {}
        for key, default, lo in (
                (c.TRAINING_HEALTH_WARMUP_STEPS,
                 c.TRAINING_HEALTH_WARMUP_STEPS_DEFAULT, 0),
                (c.TRAINING_HEALTH_ROLLBACK_AFTER,
                 c.TRAINING_HEALTH_ROLLBACK_AFTER_DEFAULT, 1),
                (c.TRAINING_HEALTH_ABORT_AFTER,
                 c.TRAINING_HEALTH_ABORT_AFTER_DEFAULT, 1),
                (c.TRAINING_HEALTH_MAX_ROLLBACKS,
                 c.TRAINING_HEALTH_MAX_ROLLBACKS_DEFAULT, 0)):
            value = as_int(th.get(key, default), f"training_health.{key}")
            if value < lo:
                raise DeepSpeedConfigError(
                    f"training_health.{key} must be >= {lo}, got {value}")
            ints[key] = value

        if enabled and policy == "rollback" and \
                self.checkpoint_config["save_dir"] is None:
            raise DeepSpeedConfigError(
                "training_health.policy 'rollback' requires "
                "checkpoint.save_dir: recovery restores the last "
                "committed checkpoint from there")

        fault_spec = th.get(c.TRAINING_HEALTH_FAULT_INJECTION)
        if fault_spec is not None:
            from .fault_injection import validate_fault_spec
            validate_fault_spec(fault_spec)   # parse-time strictness

        self.training_health_enabled = enabled
        self.training_health_config = {
            "enabled": enabled,
            "policy": policy,
            "loss_zscore": floats[c.TRAINING_HEALTH_LOSS_ZSCORE],
            "grad_norm_zscore":
                floats[c.TRAINING_HEALTH_GRAD_NORM_ZSCORE],
            "ema_beta": floats[c.TRAINING_HEALTH_EMA_BETA],
            "warmup_steps": ints[c.TRAINING_HEALTH_WARMUP_STEPS],
            "rollback_after": ints[c.TRAINING_HEALTH_ROLLBACK_AFTER],
            "abort_after": ints[c.TRAINING_HEALTH_ABORT_AFTER],
            "max_rollbacks": ints[c.TRAINING_HEALTH_MAX_ROLLBACKS],
            "hang_timeout_seconds": floats[c.TRAINING_HEALTH_HANG_TIMEOUT],
            "fault_injection": fault_spec,
        }

    def _parse_telemetry_block(self, d):
        """Parse + validate the "telemetry" block (runtime/telemetry.py:
        span tracing, goodput + MFU accounting, trigger-driven profiler
        capture). Same parse-time strictness as the "checkpoint" /
        "training_health" blocks: a mistyped capture window must fail at
        startup, not silently never trace."""
        tel = d.get(c.TELEMETRY) or {}
        known = {c.TELEMETRY_ENABLED, c.TELEMETRY_GOODPUT, c.TELEMETRY_MFU,
                 c.TELEMETRY_SPANS, c.TELEMETRY_TRACE_DIR,
                 c.TELEMETRY_CAPTURE, c.TELEMETRY_MEMORY_WATERMARK_INTERVAL,
                 c.TELEMETRY_CAPTURE_ON_ANOMALY,
                 c.TELEMETRY_ANOMALY_CAPTURE_STEPS, c.TELEMETRY_FLEET}
        unknown = sorted(set(tel) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'telemetry' key(s) {unknown}; valid keys: "
                f"{sorted(known)}")

        bools = {}
        for key, default in (
                (c.TELEMETRY_ENABLED, c.TELEMETRY_ENABLED_DEFAULT),
                (c.TELEMETRY_GOODPUT, c.TELEMETRY_GOODPUT_DEFAULT),
                (c.TELEMETRY_MFU, c.TELEMETRY_MFU_DEFAULT),
                (c.TELEMETRY_SPANS, c.TELEMETRY_SPANS_DEFAULT),
                (c.TELEMETRY_CAPTURE_ON_ANOMALY,
                 c.TELEMETRY_CAPTURE_ON_ANOMALY_DEFAULT)):
            value = tel.get(key, default)
            if not isinstance(value, bool):
                raise DeepSpeedConfigError(
                    f"telemetry.{key} must be a boolean, got {value!r}")
            bools[key] = value

        trace_dir = tel.get(c.TELEMETRY_TRACE_DIR,
                            c.TELEMETRY_TRACE_DIR_DEFAULT)
        if trace_dir is not None and not isinstance(trace_dir, str):
            raise DeepSpeedConfigError(
                f"telemetry.{c.TELEMETRY_TRACE_DIR} must be a string "
                f"path, got {trace_dir!r}")

        capture = tel.get(c.TELEMETRY_CAPTURE)
        if capture is not None:
            if not isinstance(capture, dict):
                raise DeepSpeedConfigError(
                    f"telemetry.{c.TELEMETRY_CAPTURE} must be an object "
                    "{start_step, num_steps}, got "
                    f"{type(capture).__name__}")
            cap_known = {c.TELEMETRY_CAPTURE_START_STEP,
                         c.TELEMETRY_CAPTURE_NUM_STEPS}
            cap_unknown = sorted(set(capture) - cap_known)
            if cap_unknown:
                raise DeepSpeedConfigError(
                    f"Unknown telemetry.{c.TELEMETRY_CAPTURE} key(s) "
                    f"{cap_unknown}; valid keys: {sorted(cap_known)}")
            if c.TELEMETRY_CAPTURE_START_STEP not in capture:
                raise DeepSpeedConfigError(
                    f"telemetry.{c.TELEMETRY_CAPTURE} requires "
                    f"{c.TELEMETRY_CAPTURE_START_STEP}")
            start = as_int(capture[c.TELEMETRY_CAPTURE_START_STEP],
                           f"telemetry.capture."
                           f"{c.TELEMETRY_CAPTURE_START_STEP}")
            num = as_int(capture.get(c.TELEMETRY_CAPTURE_NUM_STEPS,
                                     c.TELEMETRY_CAPTURE_NUM_STEPS_DEFAULT),
                         f"telemetry.capture."
                         f"{c.TELEMETRY_CAPTURE_NUM_STEPS}")
            if start < 0:
                raise DeepSpeedConfigError(
                    f"telemetry.capture.{c.TELEMETRY_CAPTURE_START_STEP} "
                    f"must be >= 0, got {start}")
            if num < 1:
                raise DeepSpeedConfigError(
                    f"telemetry.capture.{c.TELEMETRY_CAPTURE_NUM_STEPS} "
                    f"must be >= 1, got {num}")
            capture = {c.TELEMETRY_CAPTURE_START_STEP: start,
                       c.TELEMETRY_CAPTURE_NUM_STEPS: num}

        watermark = as_int(
            tel.get(c.TELEMETRY_MEMORY_WATERMARK_INTERVAL,
                    c.TELEMETRY_MEMORY_WATERMARK_INTERVAL_DEFAULT),
            f"telemetry.{c.TELEMETRY_MEMORY_WATERMARK_INTERVAL}")
        if watermark < 0:
            raise DeepSpeedConfigError(
                f"telemetry.{c.TELEMETRY_MEMORY_WATERMARK_INTERVAL} must "
                f"be >= 0 (0 disables), got {watermark}")
        anomaly_steps = as_int(
            tel.get(c.TELEMETRY_ANOMALY_CAPTURE_STEPS,
                    c.TELEMETRY_ANOMALY_CAPTURE_STEPS_DEFAULT),
            f"telemetry.{c.TELEMETRY_ANOMALY_CAPTURE_STEPS}")
        if anomaly_steps < 1:
            raise DeepSpeedConfigError(
                f"telemetry.{c.TELEMETRY_ANOMALY_CAPTURE_STEPS} must be "
                f">= 1, got {anomaly_steps}")

        # module-level helper: the InferenceEngine reuses this parser
        # with a bare namespace as `self`
        fleet = _parse_telemetry_fleet(tel)

        needs_dir = capture is not None or \
            bools[c.TELEMETRY_CAPTURE_ON_ANOMALY]
        if bools[c.TELEMETRY_ENABLED] and needs_dir and trace_dir is None:
            raise DeepSpeedConfigError(
                f"telemetry.{c.TELEMETRY_TRACE_DIR} is required when "
                f"'{c.TELEMETRY_CAPTURE}' or "
                f"'{c.TELEMETRY_CAPTURE_ON_ANOMALY}' is set (captures "
                "need somewhere to write)")

        self.telemetry_enabled = bools[c.TELEMETRY_ENABLED]
        self.telemetry_config = {
            "enabled": bools[c.TELEMETRY_ENABLED],
            "goodput": bools[c.TELEMETRY_GOODPUT],
            "mfu": bools[c.TELEMETRY_MFU],
            "spans": bools[c.TELEMETRY_SPANS],
            "trace_dir": trace_dir,
            "capture": capture,
            "memory_watermark_interval_steps": watermark,
            "capture_on_anomaly": bools[c.TELEMETRY_CAPTURE_ON_ANOMALY],
            "anomaly_capture_steps": anomaly_steps,
            "fleet": fleet,
        }

    def _parse_monitor_block(self, d):
        """Parse + validate the ``monitor`` block (runtime/exporters.py:
        the Prometheus endpoint, the JSONL event stream, and event-file
        rotation). Same parse-time strictness as the telemetry block —
        a mistyped port must fail at startup, not silently never serve
        a scrape."""
        mon = d.get(c.MONITOR) or {}
        known = {c.MONITOR_EXPORT}
        unknown = sorted(set(mon) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'monitor' key(s) {unknown}; valid keys: "
                f"{sorted(known)}")
        exp = mon.get(c.MONITOR_EXPORT) or {}
        if not isinstance(exp, dict):
            raise DeepSpeedConfigError(
                f"monitor.{c.MONITOR_EXPORT} must be an object, got "
                f"{type(exp).__name__}")
        exp_known = {c.MONITOR_PROMETHEUS_PORT, c.MONITOR_PROMETHEUS_HOST,
                     c.MONITOR_JSONL, c.MONITOR_ROTATE_MAX_MB,
                     c.MONITOR_ROTATE_KEEP}
        exp_unknown = sorted(set(exp) - exp_known)
        if exp_unknown:
            raise DeepSpeedConfigError(
                f"Unknown monitor.{c.MONITOR_EXPORT} key(s) "
                f"{exp_unknown}; valid keys: {sorted(exp_known)}")
        port = exp.get(c.MONITOR_PROMETHEUS_PORT,
                       c.MONITOR_PROMETHEUS_PORT_DEFAULT)
        if port is not None:
            port = as_int(port,
                          f"monitor.export.{c.MONITOR_PROMETHEUS_PORT}")
            if not 0 <= port <= 65535:
                raise DeepSpeedConfigError(
                    f"monitor.export.{c.MONITOR_PROMETHEUS_PORT} must be "
                    f"in [0, 65535] (0 = ephemeral), got {port}")
        jsonl = exp.get(c.MONITOR_JSONL, c.MONITOR_JSONL_DEFAULT)
        if not isinstance(jsonl, bool):
            raise DeepSpeedConfigError(
                f"monitor.export.{c.MONITOR_JSONL} must be a boolean, "
                f"got {jsonl!r}")
        try:
            rotate_mb = float(exp.get(c.MONITOR_ROTATE_MAX_MB,
                                      c.MONITOR_ROTATE_MAX_MB_DEFAULT))
        except (TypeError, ValueError):
            raise DeepSpeedConfigError(
                f"monitor.export.{c.MONITOR_ROTATE_MAX_MB} must be a "
                f"number (MB; 0 disables rotation), got "
                f"{exp.get(c.MONITOR_ROTATE_MAX_MB)!r}")
        if rotate_mb < 0:
            raise DeepSpeedConfigError(
                f"monitor.export.{c.MONITOR_ROTATE_MAX_MB} must be >= 0, "
                f"got {rotate_mb}")
        keep = as_int(exp.get(c.MONITOR_ROTATE_KEEP,
                              c.MONITOR_ROTATE_KEEP_DEFAULT),
                      f"monitor.export.{c.MONITOR_ROTATE_KEEP}")
        if keep < 1:
            raise DeepSpeedConfigError(
                f"monitor.export.{c.MONITOR_ROTATE_KEEP} must be >= 1, "
                f"got {keep}")
        host = exp.get(c.MONITOR_PROMETHEUS_HOST,
                       c.MONITOR_PROMETHEUS_HOST_DEFAULT)
        if not isinstance(host, str) or not host:
            raise DeepSpeedConfigError(
                f"monitor.export.{c.MONITOR_PROMETHEUS_HOST} must be a "
                f"non-empty bind address string (default 127.0.0.1; "
                f"0.0.0.0 exposes the scrape off-box), got {host!r}")
        self.monitor_export_config = {
            "prometheus_port": port,
            "prometheus_host": host,
            "jsonl": jsonl,
            "rotate_max_mb": rotate_mb,
            "rotate_keep": keep,
        }
        # an armed export backend means the user wants the monitor even
        # without a tensorboard block — the engine constructs it either
        # way (the parser's contract: a configured exporter must serve,
        # not silently depend on an unrelated block)
        self.monitor_export_active = port is not None or jsonl

    def _parse_packing_block(self, d):
        """Parse + validate the "packing" block (runtime/packing.py:
        document-packed ragged batches with segment ids). Same parse-time
        strictness as the "checkpoint"/"moe" blocks: a typo'd knob must
        fail at startup, not silently train with cross-document
        attention. The block makes the model families REQUIRE
        (tokens, labels, segment_ids) batches — a missing segment_ids is
        then a loud error instead of silent pad-token flops."""
        pk = d.get(c.PACKING) or {}
        known = {c.PACKING_ENABLED, c.PACKING_PAD_ID, c.PACKING_DROP_TAIL}
        unknown = sorted(set(pk) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown 'packing' key(s) {unknown}; valid keys: "
                f"{sorted(known)}")

        enabled = pk.get(c.PACKING_ENABLED, c.PACKING_ENABLED_DEFAULT)
        if not isinstance(enabled, bool):
            raise DeepSpeedConfigError(
                f"packing.{c.PACKING_ENABLED} must be a boolean, got "
                f"{enabled!r}")
        self.packing_enabled = enabled
        if not enabled:
            self.packing_params = False
            return

        pad_id = as_int(pk.get(c.PACKING_PAD_ID, c.PACKING_PAD_ID_DEFAULT),
                        f"packing.{c.PACKING_PAD_ID}")
        if pad_id < 0:
            raise DeepSpeedConfigError(
                f"packing.{c.PACKING_PAD_ID} must be >= 0, got {pad_id}")
        drop_tail = pk.get(c.PACKING_DROP_TAIL,
                           c.PACKING_DROP_TAIL_DEFAULT)
        if not isinstance(drop_tail, bool):
            raise DeepSpeedConfigError(
                f"packing.{c.PACKING_DROP_TAIL} must be a boolean, got "
                f"{drop_tail!r}")
        if self.sparse_attention:
            # the block-sparse kernels carry no segment gate: a packed
            # batch through them would silently attend across documents
            raise DeepSpeedConfigError(
                "packing cannot be combined with sparse_attention: the "
                "sparse kernels are not segment-aware (use the dense "
                "segmented flash engine for packed batches)")
        self.packing_params = {
            "pad_id": pad_id,
            "drop_tail": drop_tail,
        }

    # -- batch triad -------------------------------------------------------

    def _configure_train_batch_size(self):
        """Resolve train_batch = micro_batch * grad_acc * dp_world
        (reference `config.py:681-756`)."""
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        world = self.world_size

        if all(v is not None for v in (train, micro, gas)):
            pass  # verified below
        elif train is not None and micro is not None:
            if train % (micro * world) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} is not divisible by "
                    f"micro_batch * world = {micro} * {world}")
            gas = train // (micro * world)
        elif train is not None and gas is not None:
            if train % (gas * world) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} is not divisible by "
                    f"grad_acc * world = {gas} * {world}")
            micro = train // (gas * world)
        elif micro is not None:
            gas = gas if gas is not None else 1
            train = micro * gas * world
        elif train is not None:
            micro = train // world
            gas = 1
        elif gas is not None:
            raise DeepSpeedConfigError(
                "gradient_accumulation_steps alone cannot determine batch "
                "sizes; also provide train_batch_size or "
                "train_micro_batch_size_per_gpu")
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size or "
                "train_micro_batch_size_per_gpu must be configured")

        self.train_batch_size = as_int(train, c.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = as_int(
            micro, c.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = as_int(
            gas, c.GRADIENT_ACCUMULATION_STEPS)

    # -- validation --------------------------------------------------------

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def _do_error_check(self):
        train, micro, gas = (self.train_batch_size,
                             self.train_micro_batch_size_per_gpu,
                             self.gradient_accumulation_steps)
        for name, value in ((c.TRAIN_BATCH_SIZE, train),
                            (c.TRAIN_MICRO_BATCH_SIZE_PER_GPU, micro),
                            (c.GRADIENT_ACCUMULATION_STEPS, gas)):
            if value <= 0:
                raise DeepSpeedConfigError(f"{name} must be > 0, got {value}")
        if train != micro * gas * self.world_size:
            raise DeepSpeedConfigError(
                f"Check batch related parameters. "
                f"train_batch_size ({train}) is not equal to "
                f"micro_batch_per_gpu ({micro}) * grad_acc ({gas}) * "
                f"world_size ({self.world_size})")
        if self.zero_enabled and \
                self.zero_optimization_stage > len([1, 2, 3]):
            raise DeepSpeedConfigError(
                f"Max ZeRO stage is 3, got {self.zero_optimization_stage}")

    def _do_warning_check(self):
        if self.fp16_enabled and not self.bfloat16_enabled:
            logger.debug("fp16 enabled: dynamic loss scaling active")
        if (self.gradient_clipping > 0 and self.optimizer_params and
                c.MAX_GRAD_NORM in self.optimizer_params):
            logger.warning(
                f"optimizer params include {c.MAX_GRAD_NORM}; DeepSpeed-style "
                "gradient clipping from 'gradient_clipping' takes precedence")

    # -- misc --------------------------------------------------------------

    @property
    def param_dict(self):
        return self._param_dict

    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        for key in sorted(self.__dict__):
            if key.startswith("_"):
                continue
            logger.info(f"  {key} {self.__dict__[key]}")


def _default_dp_world_size():
    """Data-parallel world size when no mpu/topology is supplied: the number
    of addressable devices (the launcher exports one process per host; each
    process drives all local chips)."""
    try:
        import jax
        return jax.device_count()
    except Exception:
        return 1


def _parse_telemetry_fleet(tel):
    """Validate the ``telemetry.fleet`` sub-block (runtime/fleet.py:
    cross-host aggregation windows, the collective-skew probe, and the
    merged-capture event bound). Module-level (not a method): the
    InferenceEngine drives `_parse_telemetry_block` with a bare
    namespace as ``self``. Returns the params dict, or None when the
    sub-block is absent/disabled."""
    fl = tel.get(c.TELEMETRY_FLEET)
    if fl is None:
        return None
    if not isinstance(fl, dict):
        raise DeepSpeedConfigError(
            f"telemetry.{c.TELEMETRY_FLEET} must be an object, got "
            f"{type(fl).__name__}")
    known = {c.TELEMETRY_FLEET_ENABLED,
             c.TELEMETRY_FLEET_WINDOW_STEPS,
             c.TELEMETRY_FLEET_SKEW_INTERVAL,
             c.TELEMETRY_FLEET_SKEW_EMA_BETA,
             c.TELEMETRY_FLEET_SKEW_THRESHOLD_MS,
             c.TELEMETRY_FLEET_MAX_TRACE_EVENTS}
    unknown = sorted(set(fl) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown telemetry.{c.TELEMETRY_FLEET} key(s) {unknown}; "
            f"valid keys: {sorted(known)}")
    enabled = fl.get(c.TELEMETRY_FLEET_ENABLED,
                     c.TELEMETRY_FLEET_ENABLED_DEFAULT)
    if not isinstance(enabled, bool):
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_ENABLED} must be a "
            f"boolean, got {enabled!r}")
    window = as_int(fl.get(c.TELEMETRY_FLEET_WINDOW_STEPS,
                           c.TELEMETRY_FLEET_WINDOW_STEPS_DEFAULT),
                    f"telemetry.fleet.{c.TELEMETRY_FLEET_WINDOW_STEPS}")
    if window < 1:
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_WINDOW_STEPS} must be "
            f">= 1, got {window}")
    skew_interval = as_int(
        fl.get(c.TELEMETRY_FLEET_SKEW_INTERVAL,
               c.TELEMETRY_FLEET_SKEW_INTERVAL_DEFAULT),
        f"telemetry.fleet.{c.TELEMETRY_FLEET_SKEW_INTERVAL}")
    if skew_interval < 0:
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_SKEW_INTERVAL} must be "
            f">= 0 (0 disables the probe), got {skew_interval}")
    try:
        beta = float(fl.get(c.TELEMETRY_FLEET_SKEW_EMA_BETA,
                            c.TELEMETRY_FLEET_SKEW_EMA_BETA_DEFAULT))
    except (TypeError, ValueError):
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_SKEW_EMA_BETA} must be "
            f"a number, got {fl.get(c.TELEMETRY_FLEET_SKEW_EMA_BETA)!r}")
    if not 0.0 <= beta < 1.0:
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_SKEW_EMA_BETA} must be "
            f"in [0, 1), got {beta}")
    try:
        threshold = float(
            fl.get(c.TELEMETRY_FLEET_SKEW_THRESHOLD_MS,
                   c.TELEMETRY_FLEET_SKEW_THRESHOLD_MS_DEFAULT))
    except (TypeError, ValueError):
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_SKEW_THRESHOLD_MS} "
            f"must be a number, got "
            f"{fl.get(c.TELEMETRY_FLEET_SKEW_THRESHOLD_MS)!r}")
    if threshold < 0:
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_SKEW_THRESHOLD_MS} "
            f"must be >= 0, got {threshold}")
    max_events = as_int(
        fl.get(c.TELEMETRY_FLEET_MAX_TRACE_EVENTS,
               c.TELEMETRY_FLEET_MAX_TRACE_EVENTS_DEFAULT),
        f"telemetry.fleet.{c.TELEMETRY_FLEET_MAX_TRACE_EVENTS}")
    if max_events < 1:
        raise DeepSpeedConfigError(
            f"telemetry.fleet.{c.TELEMETRY_FLEET_MAX_TRACE_EVENTS} must "
            f"be >= 1, got {max_events}")
    if not enabled:
        return None
    return {
        "enabled": True,
        "window_steps": window,
        "skew_interval_steps": skew_interval,
        "skew_ema_beta": beta,
        "skew_slow_threshold_ms": threshold,
        "max_trace_events": max_events,
    }
