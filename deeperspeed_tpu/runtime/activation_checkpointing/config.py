"""Activation-checkpointing config block (reference:
`deepspeed/runtime/activation_checkpointing/config.py`).

On TPU these knobs steer `jax.checkpoint` policies: `partition_activations`
shards saved residuals over the `model` axis, `cpu_checkpointing` selects a
host-offload remat policy, and `contiguous_memory_optimization` /
`synchronize_checkpoint_boundary` are accepted as no-ops (XLA owns layout
and scheduling).
"""

from dataclasses import dataclass

from ..config_utils import DeepSpeedConfigError, get_scalar_param

ACT_CHKPT = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
# Fork key: named jax.checkpoint rematerialization policy (see
# checkpointing.make_remat_policy for semantics).
ACT_CHKPT_POLICY = "policy"

ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CHKPT_PROFILE_DEFAULT = False
ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT = False
ACT_CHKPT_POLICY_DEFAULT = None

REMAT_POLICY_CHOICES = ("none", "full", "dots", "attn_residuals",
                        "offload_dots")


def _validate_number_checkpoints(value):
    """Parse-time check: a positive int or None. The model-side cap
    (<= num_layers) is enforced where the layer count is known
    (`apply_ds_config`)."""
    if value is None:
        return None
    if isinstance(value, bool) or not isinstance(value, int):
        raise DeepSpeedConfigError(
            f"{ACT_CHKPT}.{ACT_CHKPT_NUMBER_CHECKPOINTS} must be a "
            f"positive int or null, got {value!r}")
    if value <= 0:
        raise DeepSpeedConfigError(
            f"{ACT_CHKPT}.{ACT_CHKPT_NUMBER_CHECKPOINTS} must be > 0, "
            f"got {value}")
    return value


def _validate_policy(value):
    if value is None:
        return None
    if value not in REMAT_POLICY_CHOICES:
        raise DeepSpeedConfigError(
            f"{ACT_CHKPT}.{ACT_CHKPT_POLICY}: unknown remat policy "
            f"{value!r}; valid choices: {', '.join(REMAT_POLICY_CHOICES)}")
    return value


@dataclass(frozen=True)
class DeepSpeedActivationCheckpointingConfig:
    partition_activations: bool = ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT
    number_checkpoints: object = ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT
    contiguous_memory_optimization: bool = (
        ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
    synchronize_checkpoint_boundary: bool = (
        ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)
    profile: bool = ACT_CHKPT_PROFILE_DEFAULT
    cpu_checkpointing: bool = ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT
    policy: object = ACT_CHKPT_POLICY_DEFAULT

    @property
    def active(self):
        """True when the block asks for anything beyond the defaults that
        the engine must thread into the model forward."""
        return (self.policy is not None
                or self.number_checkpoints is not None
                or self.partition_activations
                or self.cpu_checkpointing)

    @classmethod
    def from_dict(cls, param_dict):
        d = param_dict.get(ACT_CHKPT) or {}
        policy = _validate_policy(get_scalar_param(
            d, ACT_CHKPT_POLICY, ACT_CHKPT_POLICY_DEFAULT))
        cpu = bool(get_scalar_param(d, ACT_CHKPT_CPU_CHECKPOINTING,
                                    ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT))
        if cpu and policy in ("none", "full", "attn_residuals"):
            # cpu_checkpointing promotes the (default/'dots') policy to
            # its host-offload form; with these policies there is no
            # offloadable save set — silently ignoring either knob would
            # hide a misconfiguration
            raise DeepSpeedConfigError(
                f"{ACT_CHKPT}: cpu_checkpointing=true conflicts with "
                f"policy={policy!r} (nothing it saves can offload); use "
                "policy 'dots'/'offload_dots' or drop cpu_checkpointing")
        return cls(
            partition_activations=bool(get_scalar_param(
                d, ACT_CHKPT_PARTITION_ACTIVATIONS,
                ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT)),
            number_checkpoints=_validate_number_checkpoints(
                get_scalar_param(d, ACT_CHKPT_NUMBER_CHECKPOINTS,
                                 ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT)),
            contiguous_memory_optimization=bool(get_scalar_param(
                d, ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
                ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)),
            synchronize_checkpoint_boundary=bool(get_scalar_param(
                d, ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
                ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)),
            profile=bool(get_scalar_param(
                d, ACT_CHKPT_PROFILE, ACT_CHKPT_PROFILE_DEFAULT)),
            cpu_checkpointing=cpu,
            policy=policy,
        )
