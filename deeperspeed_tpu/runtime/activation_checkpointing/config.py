"""Activation-checkpointing config block (reference:
`deepspeed/runtime/activation_checkpointing/config.py`).

On TPU these knobs steer `jax.checkpoint` policies: `partition_activations`
shards saved residuals over the `model` axis, `cpu_checkpointing` selects a
host-offload remat policy, and `contiguous_memory_optimization` /
`synchronize_checkpoint_boundary` are accepted as no-ops (XLA owns layout
and scheduling).
"""

from dataclasses import dataclass

from ..config_utils import get_scalar_param

ACT_CHKPT = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"

ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CHKPT_PROFILE_DEFAULT = False
ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT = False


@dataclass(frozen=True)
class DeepSpeedActivationCheckpointingConfig:
    partition_activations: bool = ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT
    number_checkpoints: object = ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT
    contiguous_memory_optimization: bool = (
        ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
    synchronize_checkpoint_boundary: bool = (
        ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)
    profile: bool = ACT_CHKPT_PROFILE_DEFAULT
    cpu_checkpointing: bool = ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT

    @classmethod
    def from_dict(cls, param_dict):
        d = param_dict.get(ACT_CHKPT) or {}
        return cls(
            partition_activations=bool(get_scalar_param(
                d, ACT_CHKPT_PARTITION_ACTIVATIONS,
                ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT)),
            number_checkpoints=get_scalar_param(
                d, ACT_CHKPT_NUMBER_CHECKPOINTS,
                ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT),
            contiguous_memory_optimization=bool(get_scalar_param(
                d, ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
                ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)),
            synchronize_checkpoint_boundary=bool(get_scalar_param(
                d, ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
                ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)),
            profile=bool(get_scalar_param(
                d, ACT_CHKPT_PROFILE, ACT_CHKPT_PROFILE_DEFAULT)),
            cpu_checkpointing=bool(get_scalar_param(
                d, ACT_CHKPT_CPU_CHECKPOINTING,
                ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT)),
        )
