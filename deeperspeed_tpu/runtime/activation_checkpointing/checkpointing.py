"""Activation checkpointing (reference:
`deepspeed/runtime/activation_checkpointing/checkpointing.py`).

The reference reimplements Megatron's checkpointing: recompute-in-backward
with CUDA RNG state capture/restore (`CudaRNGStatesTracker`), optional
partitioning of saved activations across model-parallel ranks, CPU offload
of checkpoints, and contiguous preallocated buffers.

On TPU each concern maps to a JAX-native mechanism:

- recompute-in-backward         → `jax.checkpoint` (remat).
- RNG capture/restore           → free: JAX PRNG keys are explicit values,
  so recomputation replays dropout identically by construction. The
  tracker API is kept for Megatron-style callers.
- partition_activations         → saved residuals carry a `model`-axis
  sharding constraint, so each MP rank stores 1/mp of every checkpoint.
- cpu_checkpointing             → 'offload_dots' remat policy: saved
  matmul results rest in pinned host memory
  (`offload_dot_with_no_batch_dims`). Host-offload transfers only exist
  under `jax.jit` — eager `jax.grad` over an offloading span raises
  (real training is always jitted).
- contiguous_memory_optimization / synchronize_checkpoint_boundary →
  no-ops: XLA owns allocation and scheduling.
"""

from functools import partial

import jax
import jax.numpy as jnp

from ...utils.logging import logger
from .config import REMAT_POLICY_CHOICES, DeepSpeedActivationCheckpointingConfig

_config = DeepSpeedActivationCheckpointingConfig()
_mpu = None
_configured = False

# ---------------------------------------------------------------------------
# Named remat policies. The JSON `activation_checkpointing.policy` key (and
# the model families' `remat_policy=` knob) select one by name; the model
# forward threads the resolved policy into every `jax.checkpoint` span.
#
# Residual-name tags: the flash-attention custom_vjp fwd marks its saved
# output/LSE with these names so `attn_residuals` can pin exactly the
# tensors the Pallas backward kernels consume — the bwd then never re-runs
# the forward kernel under remat.
# ---------------------------------------------------------------------------

ATTN_OUT_NAME = "ds_attn_out"
ATTN_LSE_NAME = "ds_attn_lse"


def tag_attn_residual(x, name=ATTN_OUT_NAME):
    """Mark an attention residual for name-based remat policies. A no-op
    outside `jax.checkpoint` spans (and for policies that ignore names).

    Inside `shard_map` with the replication check on, jax 0.4.37 has no
    rep rule for the `name` primitive and raises at trace time — the tag
    is dropped there (name-based policies then degrade to recompute for
    that region; every other policy is unaffected)."""
    from jax.ad_checkpoint import checkpoint_name
    try:
        return checkpoint_name(x, name)
    except NotImplementedError:
        return x


def make_remat_policy(name, offload_src="device", offload_dst="pinned_host"):
    """Named policy -> `jax.checkpoint` policy callable.

    Returns `(policy, is_remat)`: `policy` feeds jax.checkpoint's
    `policy=` (None = save nothing, today's whole-block behavior);
    `is_remat=False` only for 'none', which saves everything — callers may
    skip the checkpoint wrapper entirely.

    - none:           save every intermediate (remat disabled).
    - full:           save nothing; recompute the whole span in backward.
    - dots:           save matmul results excluding batch dims (the
                      classic activations-not-weights split).
    - attn_residuals: save only the flash-attention outputs + LSE
                      (`ATTN_OUT_NAME`/`ATTN_LSE_NAME` tags) so the
                      Pallas bwd kernel never re-runs its forward.
    - offload_dots:   'dots', but saved dots rest in host memory
                      (ZeRO-Offload for activations; honors
                      `cpu_checkpointing`).
    """
    if name is None or name == "full":
        return None, True
    cp = jax.checkpoint_policies
    if name == "none":
        return cp.everything_saveable, False
    if name == "dots":
        return cp.dots_with_no_batch_dims_saveable, True
    if name == "attn_residuals":
        return cp.save_only_these_names(ATTN_OUT_NAME, ATTN_LSE_NAME), True
    if name == "offload_dots":
        offload = getattr(cp, "offload_dot_with_no_batch_dims", None)
        if offload is None:  # pragma: no cover - old-jax fallback
            logger.warning(
                "offload_dot_with_no_batch_dims unavailable on this jax; "
                "remat policy 'offload_dots' degrades to on-device 'dots'")
            return cp.dots_with_no_batch_dims_saveable, True
        return offload(offload_src, offload_dst), True
    raise ValueError(
        f"unknown remat policy {name!r}; valid choices: "
        f"{', '.join(REMAT_POLICY_CHOICES)}")


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Configure the checkpointing subsystem (reference
    `checkpointing.py:769`)."""
    global _config, _mpu, _configured
    _mpu = mpu_
    if deepspeed_config is not None:
        if hasattr(deepspeed_config, "activation_checkpointing_config"):
            _config = deepspeed_config.activation_checkpointing_config
        else:
            _config = DeepSpeedActivationCheckpointingConfig.from_dict(
                deepspeed_config if isinstance(deepspeed_config, dict)
                else {})
    overrides = {
        "partition_activations": partition_activations,
        "contiguous_memory_optimization": contiguous_checkpointing,
        "number_checkpoints": num_checkpoints,
        "cpu_checkpointing": checkpoint_in_cpu,
        "synchronize_checkpoint_boundary": synchronize,
        "profile": profile,
    }
    updates = {k: v for k, v in overrides.items() if v is not None}
    if updates:
        import dataclasses
        _config = dataclasses.replace(_config, **updates)
    _configured = True


def is_configured():
    return _configured


def resolve_policy_name(policy, cpu_checkpointing):
    """The effective policy name for a config block: `cpu_checkpointing`
    promotes the (default/'dots') on-device policy to its host-offload
    form — the reference key spills checkpoints to CPU memory."""
    if cpu_checkpointing and policy in (None, "dots", "offload_dots"):
        return "offload_dots"
    return policy


def _policy():
    name = resolve_policy_name(getattr(_config, "policy", None),
                               _config.cpu_checkpointing)
    if name is None:
        return None  # full remat: save nothing, recompute everything
    return make_remat_policy(name)[0]


def checkpoint(function, *args):
    """Checkpoint a forward span: recompute it during backward (reference
    `checkpointing.py:687`). Dropout/noise inside replays identically
    because PRNG keys are explicit arguments."""
    policy = _policy()
    wrapped = jax.checkpoint(function, policy=policy) if policy is not None \
        else jax.checkpoint(function)

    if _config.partition_activations and _mpu is not None:
        axis = None
        if hasattr(_mpu, "get_slice_parallel_group"):
            axis = _mpu.get_slice_parallel_group()
        if isinstance(axis, str):
            # Shard the span inputs over the model axis so each MP rank
            # holds 1/mp of every saved checkpoint (reference
            # `partition_activations` semantics).
            from jax.sharding import PartitionSpec

            def constrain(x):
                if hasattr(x, "ndim") and x.ndim >= 2:
                    spec = [None] * x.ndim
                    spec[1] = axis
                    try:
                        return jax.lax.with_sharding_constraint(
                            x, PartitionSpec(*spec))
                    except Exception:
                        return x
                return x

            args = tuple(jax.tree_util.tree_map(constrain, a)
                         for a in args)
    return wrapped(*args)


def checkpoint_wrapper(fn):
    """Decorator form."""
    return partial(checkpoint, fn)


# ---------------------------------------------------------------------------
# RNG tracker API (reference `checkpointing.py:198`-): Megatron callers
# expect named RNG states whose capture/restore makes dropout reproducible
# under recompute. With JAX's explicit keys this is bookkeeping only.
# ---------------------------------------------------------------------------

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class CudaRNGStatesTracker:
    """Named PRNG key registry (name kept for API compatibility)."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise Exception(f"seed {seed} already present")
        self.seeds_.add(seed)
        if name in self.states_:
            raise Exception(f"RNG state {name} already present")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Context manager yielding the named key; the stored state is
        advanced so successive forks differ."""
        import contextlib

        @contextlib.contextmanager
        def _fork():
            if name not in self.states_:
                raise Exception(f"RNG state {name} is not added")
            key, sub = jax.random.split(self.states_[name])
            self.states_[name] = key
            yield sub

        return _fork()


_CUDA_RNG_STATE_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker():
    return _CUDA_RNG_STATE_TRACKER


def model_parallel_cuda_manual_seed(seed):
    """Seed data-parallel and model-parallel RNG streams (reference
    `checkpointing.py:198`): MP ranks get offset seeds so dropout differs
    across tensor-parallel shards of one layer."""
    global _CUDA_RNG_STATE_TRACKER
    mp_rank = 0
    if _mpu is not None and hasattr(_mpu, "get_slice_parallel_rank"):
        mp_rank = _mpu.get_slice_parallel_rank()
    offset = seed + 2718
    model_parallel_seed = offset + mp_rank
    _CUDA_RNG_STATE_TRACKER.reset()
    _CUDA_RNG_STATE_TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME,
                                model_parallel_seed)
    return jax.random.PRNGKey(seed)


def reset():
    """Reset between batches (reference keeps buffers; we keep nothing)."""


def partition_activations_in_checkpoint(partition_activation):
    import dataclasses
    global _config
    _config = dataclasses.replace(
        _config, partition_activations=partition_activation)
    logger.info(f"**************Partition Activations "
                f"{partition_activation}************")
