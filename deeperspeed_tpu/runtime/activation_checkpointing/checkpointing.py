"""Activation checkpointing (reference:
`deepspeed/runtime/activation_checkpointing/checkpointing.py`).

The reference reimplements Megatron's checkpointing: recompute-in-backward
with CUDA RNG state capture/restore (`CudaRNGStatesTracker`), optional
partitioning of saved activations across model-parallel ranks, CPU offload
of checkpoints, and contiguous preallocated buffers.

On TPU each concern maps to a JAX-native mechanism:

- recompute-in-backward         → `jax.checkpoint` (remat).
- RNG capture/restore           → free: JAX PRNG keys are explicit values,
  so recomputation replays dropout identically by construction. The
  tracker API is kept for Megatron-style callers.
- partition_activations         → saved residuals carry a `model`-axis
  sharding constraint, so each MP rank stores 1/mp of every checkpoint.
- cpu_checkpointing             → remat policy offloads saved dots to
  host memory (`save_and_offload_only_these_names` / device_put policy).
- contiguous_memory_optimization / synchronize_checkpoint_boundary →
  no-ops: XLA owns allocation and scheduling.
"""

from functools import partial

import jax
import jax.numpy as jnp

from ...utils.logging import logger
from .config import DeepSpeedActivationCheckpointingConfig

_config = DeepSpeedActivationCheckpointingConfig()
_mpu = None
_configured = False

# Offload saved residuals to host when cpu_checkpointing is on.
_CPU_POLICY = jax.checkpoint_policies.save_and_offload_only_these_names(
    names_which_can_be_saved=[],
    names_which_can_be_offloaded=["ds_checkpoint"],
    offload_src="device", offload_dst="pinned_host")


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Configure the checkpointing subsystem (reference
    `checkpointing.py:769`)."""
    global _config, _mpu, _configured
    _mpu = mpu_
    if deepspeed_config is not None:
        if hasattr(deepspeed_config, "activation_checkpointing_config"):
            _config = deepspeed_config.activation_checkpointing_config
        else:
            _config = DeepSpeedActivationCheckpointingConfig.from_dict(
                deepspeed_config if isinstance(deepspeed_config, dict)
                else {})
    overrides = {
        "partition_activations": partition_activations,
        "contiguous_memory_optimization": contiguous_checkpointing,
        "number_checkpoints": num_checkpoints,
        "cpu_checkpointing": checkpoint_in_cpu,
        "synchronize_checkpoint_boundary": synchronize,
        "profile": profile,
    }
    updates = {k: v for k, v in overrides.items() if v is not None}
    if updates:
        import dataclasses
        _config = dataclasses.replace(_config, **updates)
    _configured = True


def is_configured():
    return _configured


def _policy():
    if _config.cpu_checkpointing:
        return _CPU_POLICY
    return None  # full remat: save nothing, recompute everything


def checkpoint(function, *args):
    """Checkpoint a forward span: recompute it during backward (reference
    `checkpointing.py:687`). Dropout/noise inside replays identically
    because PRNG keys are explicit arguments."""
    policy = _policy()
    wrapped = jax.checkpoint(function, policy=policy) if policy is not None \
        else jax.checkpoint(function)

    if _config.partition_activations and _mpu is not None:
        axis = None
        if hasattr(_mpu, "get_slice_parallel_group"):
            axis = _mpu.get_slice_parallel_group()
        if isinstance(axis, str):
            # Shard the span inputs over the model axis so each MP rank
            # holds 1/mp of every saved checkpoint (reference
            # `partition_activations` semantics).
            from jax.sharding import PartitionSpec

            def constrain(x):
                if hasattr(x, "ndim") and x.ndim >= 2:
                    spec = [None] * x.ndim
                    spec[1] = axis
                    try:
                        return jax.lax.with_sharding_constraint(
                            x, PartitionSpec(*spec))
                    except Exception:
                        return x
                return x

            args = tuple(jax.tree_util.tree_map(constrain, a)
                         for a in args)
    return wrapped(*args)


def checkpoint_wrapper(fn):
    """Decorator form."""
    return partial(checkpoint, fn)


# ---------------------------------------------------------------------------
# RNG tracker API (reference `checkpointing.py:198`-): Megatron callers
# expect named RNG states whose capture/restore makes dropout reproducible
# under recompute. With JAX's explicit keys this is bookkeeping only.
# ---------------------------------------------------------------------------

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class CudaRNGStatesTracker:
    """Named PRNG key registry (name kept for API compatibility)."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise Exception(f"seed {seed} already present")
        self.seeds_.add(seed)
        if name in self.states_:
            raise Exception(f"RNG state {name} already present")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Context manager yielding the named key; the stored state is
        advanced so successive forks differ."""
        import contextlib

        @contextlib.contextmanager
        def _fork():
            if name not in self.states_:
                raise Exception(f"RNG state {name} is not added")
            key, sub = jax.random.split(self.states_[name])
            self.states_[name] = key
            yield sub

        return _fork()


_CUDA_RNG_STATE_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker():
    return _CUDA_RNG_STATE_TRACKER


def model_parallel_cuda_manual_seed(seed):
    """Seed data-parallel and model-parallel RNG streams (reference
    `checkpointing.py:198`): MP ranks get offset seeds so dropout differs
    across tensor-parallel shards of one layer."""
    global _CUDA_RNG_STATE_TRACKER
    mp_rank = 0
    if _mpu is not None and hasattr(_mpu, "get_slice_parallel_rank"):
        mp_rank = _mpu.get_slice_parallel_rank()
    offset = seed + 2718
    model_parallel_seed = offset + mp_rank
    _CUDA_RNG_STATE_TRACKER.reset()
    _CUDA_RNG_STATE_TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME,
                                model_parallel_seed)
    return jax.random.PRNGKey(seed)


def reset():
    """Reset between batches (reference keeps buffers; we keep nothing)."""


def partition_activations_in_checkpoint(partition_activation):
    import dataclasses
    global _config
    _config = dataclasses.replace(
        _config, partition_activations=partition_activation)
    logger.info(f"**************Partition Activations "
                f"{partition_activation}************")
