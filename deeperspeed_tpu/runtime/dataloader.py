"""Data pipeline (reference: `deepspeed/runtime/dataloader.py`).

`DeepSpeedDataLoader` wraps any indexable dataset (torch Dataset, numpy
arrays, lists of pytrees) with rank-strided sampling, batching into
device-ready numpy stacks, and optional infinite repeat. The engine shards
each batch over the `data` mesh axis via NamedSharding — the loader itself
only needs to produce the *global* batch on each host process (JAX
`make_array_from_process_local_data` handles multi-host splits).
"""

import numpy as np

import jax


class RepeatingLoader:
    """Wrap an iterator to restart from the beginning when exhausted
    (reference `dataloader.py:10`; pipelines need unbounded iterators)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _stack_batch(samples):
    """Collate a list of samples (arrays or tuples/dicts of arrays)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            _stack_batch([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _stack_batch([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batched, optionally-shuffled loader producing numpy pytrees.

    `data_sampler` may be any iterable of indices; by default a
    seeded-shuffle or sequential sampler over the local shard
    (process-strided for multi-host, mirroring DistributedSampler).
    """

    def __init__(self, dataset, batch_size, collate_fn=None,
                 local_rank=None, shuffle=False, seed=0, drop_last=True,
                 data_sampler=None, num_replicas=None, rank=None,
                 tput_timer=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _stack_batch
        self.tput_timer = tput_timer
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.num_replicas = (num_replicas if num_replicas is not None
                             else jax.process_count())
        self.rank = rank if rank is not None else jax.process_index()
        # One-shot iterators (generator samplers, iter(x) is x) are
        # consumed by the first traversal — `_num_batches()` below would
        # exhaust them and `__iter__` would then yield zero batches.
        # Materialize those once; re-iterable samplers (lists, torch-style
        # sampler objects) are kept as-is so a per-epoch reshuffling
        # sampler still yields a fresh order every epoch.
        if data_sampler is not None and iter(data_sampler) is data_sampler:
            data_sampler = list(data_sampler)
        self.data_sampler = data_sampler
        self.epoch = 0
        self._batches_yielded = 0   # position within the current epoch
        self._resume_offset = 0     # batches to skip on the next __iter__
        self.len = self._num_batches()

    def _local_indices(self):
        n = len(self.dataset)
        if self.data_sampler is not None:
            return list(self.data_sampler)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        # Process-strided split (each host loads 1/num_replicas of data).
        return order[self.rank::self.num_replicas].tolist()

    def _num_batches(self):
        n = len(self._local_indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
        self.len = self._num_batches()

    def __len__(self):
        return self.len

    def position(self):
        """Current stream position as `{"epoch", "offset"}` (offset in
        batches within the epoch) — the provenance the training-health
        sentinel records for quarantined windows and rollbacks."""
        return {"epoch": self.epoch, "offset": self._batches_yielded}

    def state_dict(self):
        """Resume position for full-state checkpointing: epoch + batch
        offset. The built-in sampler's shuffle RNG is derived from
        (seed, epoch), so these two plus the seed restore the exact
        sample stream; a materialized custom `data_sampler` is static
        across epochs and needs only the offset."""
        return {"epoch": self.epoch,
                "batches_yielded": self._batches_yielded,
                "seed": self.seed,
                "shuffle": self.shuffle,
                "batch_size": self.batch_size,
                "num_replicas": self.num_replicas,
                "rank": self.rank}

    def load_state_dict(self, sd):
        self.epoch = int(sd["epoch"])
        self.seed = sd.get("seed", self.seed)
        if sd.get("batch_size") not in (None, self.batch_size):
            # a different batch size re-chunks the index stream; an
            # offset in old-batch units would resume mid-batch silently
            raise ValueError(
                f"dataloader resume: checkpoint was cut at batch_size="
                f"{sd['batch_size']} but this loader uses "
                f"{self.batch_size}; restart the epoch or match sizes")
        if "shuffle" in sd and bool(sd["shuffle"]) != bool(self.shuffle):
            # the offset skip only lands on the right samples if the
            # index ORDER matches — a flipped shuffle flag would replay
            # some samples and never see others, silently
            raise ValueError(
                f"dataloader resume: checkpoint was cut with shuffle="
                f"{sd['shuffle']} but this loader uses "
                f"shuffle={self.shuffle}")
        saved_topo = (sd.get("num_replicas", self.num_replicas),
                      sd.get("rank", self.rank))
        if saved_topo != (self.num_replicas, self.rank):
            # process-strided index streams: a different replica count or
            # rank re-deals the samples, so the offset would skip/replay
            # the wrong ones (elastic restarts hit this — the engine
            # downgrades it to a warning and a fresh epoch)
            raise ValueError(
                f"dataloader resume: checkpoint was cut at (num_replicas,"
                f" rank)={saved_topo} but this loader runs "
                f"{(self.num_replicas, self.rank)}")
        self._resume_offset = int(sd.get("batches_yielded", 0))
        self._batches_yielded = self._resume_offset
        self.len = self._num_batches()

    def reconcile_state_dict(self, sd):
        """Elastic-resume fallback when `load_state_dict` rejects the
        exact position (replica count / batch size / shuffle topology
        changed): restore only the ORDER-INDEPENDENT stream identity —
        epoch and shuffle seed — and reset the batch offset, so the
        restarted job continues with the same per-epoch sample order the
        run was configured for, re-dealt under the current topology. At
        most one partial epoch is replayed; nothing is skipped silently.
        Returns the fields kept (for the caller's warning)."""
        self.epoch = int(sd.get("epoch", self.epoch))
        self.seed = sd.get("seed", self.seed)
        self._resume_offset = 0
        self._batches_yielded = 0
        self.len = self._num_batches()
        return {"epoch": self.epoch, "seed": self.seed, "offset": 0}

    def __iter__(self):
        if self.tput_timer:
            self.tput_timer.start()
        indices = self._local_indices()
        skip, self._resume_offset = self._resume_offset, 0
        self._batches_yielded = skip
        for batch_idx, start in enumerate(
                range(0, len(indices), self.batch_size)):
            chunk = indices[start:start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                break
            if batch_idx < skip:   # checkpoint resume: mid-epoch seek
                continue
            batch = self.collate_fn([self.dataset[i] for i in chunk])
            self._batches_yielded = batch_idx + 1
            yield batch
        self.epoch += 1
        self._batches_yielded = 0
