"""Data pipeline (reference: `deepspeed/runtime/dataloader.py`).

`DeepSpeedDataLoader` wraps any indexable dataset (torch Dataset, numpy
arrays, lists of pytrees) with rank-strided sampling, batching into
device-ready numpy stacks, and optional infinite repeat. The engine shards
each batch over the `data` mesh axis via NamedSharding — the loader itself
only needs to produce the *global* batch on each host process (JAX
`make_array_from_process_local_data` handles multi-host splits).
"""

import numpy as np

import jax


class RepeatingLoader:
    """Wrap an iterator to restart from the beginning when exhausted
    (reference `dataloader.py:10`; pipelines need unbounded iterators)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _stack_batch(samples):
    """Collate a list of samples (arrays or tuples/dicts of arrays)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            _stack_batch([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _stack_batch([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batched, optionally-shuffled loader producing numpy pytrees.

    `data_sampler` may be any iterable of indices; by default a
    seeded-shuffle or sequential sampler over the local shard
    (process-strided for multi-host, mirroring DistributedSampler).
    """

    def __init__(self, dataset, batch_size, collate_fn=None,
                 local_rank=None, shuffle=False, seed=0, drop_last=True,
                 data_sampler=None, num_replicas=None, rank=None,
                 tput_timer=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _stack_batch
        self.tput_timer = tput_timer
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.num_replicas = (num_replicas if num_replicas is not None
                             else jax.process_count())
        self.rank = rank if rank is not None else jax.process_index()
        self.data_sampler = data_sampler
        self.epoch = 0
        self.len = self._num_batches()

    def _local_indices(self):
        n = len(self.dataset)
        if self.data_sampler is not None:
            return list(self.data_sampler)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        # Process-strided split (each host loads 1/num_replicas of data).
        return order[self.rank::self.num_replicas].tolist()

    def _num_batches(self):
        n = len(self._local_indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
        self.len = self._num_batches()

    def __len__(self):
        return self.len

    def __iter__(self):
        if self.tput_timer:
            self.tput_timer.start()
        indices = self._local_indices()
        for start in range(0, len(indices), self.batch_size):
            chunk = indices[start:start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                break
            yield self.collate_fn([self.dataset[i] for i in chunk])
        self.epoch += 1
