"""Config-schema key names and defaults.

The JSON schema is the reference's de-facto public API (DeeperSpeed
`deepspeed/runtime/constants.py`, `deepspeed/runtime/zero/constants.py`,
`deepspeed/runtime/zero/offload_constants.py`); GPT-NeoX configs are written
against these key strings, so they are reproduced verbatim as *names*. The
implementation behind them is TPU-native and shares nothing with the
reference.
"""

# ---------------------------------------------------------------------------
# Routes
# ---------------------------------------------------------------------------
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

# ---------------------------------------------------------------------------
# Batch-size triad
# ---------------------------------------------------------------------------
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

# ---------------------------------------------------------------------------
# Optimizer / scheduler blocks
# ---------------------------------------------------------------------------
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

# ---------------------------------------------------------------------------
# Sparse gradients (CSR embedding grads)
# ---------------------------------------------------------------------------
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

# ---------------------------------------------------------------------------
# Mixed precision ("fp16" block; the fork adds "type": "bfloat16")
# ---------------------------------------------------------------------------
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_TYPE = "type"
FP16_TYPE_DEFAULT = "fp16"
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

# Accepted spellings for "fp16.type" → canonical dtype names (jnp dtypes are
# resolved in precision.py; kept as strings here so this module stays
# import-light).
PRECISION_TYPES = {
    "fp32": "float32",
    "float32": "float32",
    "float": "float32",
    "fp16": "float16",
    "float16": "float16",
    "half": "float16",
    "bfloat16": "bfloat16",
    "bf16": "bfloat16",
}

# ---------------------------------------------------------------------------
# AMP (API-compat; on TPU this aliases bf16 autocast)
# ---------------------------------------------------------------------------
AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

# ---------------------------------------------------------------------------
# Gradient handling
# ---------------------------------------------------------------------------
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False
FP32_ALLREDUCE_DEFAULT_BF16 = True  # bf16 grads default to fp32-upcast reduce

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

# ---------------------------------------------------------------------------
# Misc top-level knobs
# ---------------------------------------------------------------------------
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

# Fork addition: gradient debugging storage (engine.py:139-141 in reference).
STORE_GRADIENTS = "store_gradients"
STORE_GRADIENTS_DEFAULT = False
STORE_GRADIENTS_CPU = "store_gradients_cpu"
STORE_GRADIENTS_CPU_DEFAULT = True

# ---------------------------------------------------------------------------
# Tensorboard
# ---------------------------------------------------------------------------
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

# ---------------------------------------------------------------------------
# Monitor export (runtime/exporters.py): scrapeable metrics backends fed by
# the monitor's single buffered drain — Prometheus HTTP endpoint +
# structured JSONL, and the TSV fallback's size-based rotation.
# ---------------------------------------------------------------------------
MONITOR = "monitor"
MONITOR_EXPORT = "export"
MONITOR_PROMETHEUS_PORT = "prometheus_port"     # None = off, 0 = ephemeral
MONITOR_PROMETHEUS_PORT_DEFAULT = None
MONITOR_PROMETHEUS_HOST = "prometheus_host"     # 0.0.0.0 = off-box scrape
MONITOR_PROMETHEUS_HOST_DEFAULT = "127.0.0.1"
MONITOR_JSONL = "jsonl"
MONITOR_JSONL_DEFAULT = False
MONITOR_ROTATE_MAX_MB = "rotate_max_mb"         # 0 disables rotation
MONITOR_ROTATE_MAX_MB_DEFAULT = 64
MONITOR_ROTATE_KEEP = "rotate_keep"
MONITOR_ROTATE_KEEP_DEFAULT = 5

# ---------------------------------------------------------------------------
# Progressive layer drop
# ---------------------------------------------------------------------------
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

# ---------------------------------------------------------------------------
# Batch-size warmup schedule (fork addition, bs_schedules.py)
# ---------------------------------------------------------------------------
BATCH_SIZE_SCHEDULE = "batch_size_schedule"
BS_SCHEDULE_ENABLED = "enabled"
BS_SCHEDULE_ENABLED_DEFAULT = False
BS_SCHEDULE_PARAMS = "params"

# ---------------------------------------------------------------------------
# Checkpoint block
# ---------------------------------------------------------------------------
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
# Fault-tolerant async checkpointing subsystem (checkpoint/async_manager):
CHECKPOINT_SAVE_DIR = "save_dir"
CHECKPOINT_SAVE_DIR_DEFAULT = None
CHECKPOINT_ASYNC_SAVE = "async_save"
CHECKPOINT_ASYNC_SAVE_DEFAULT = True
CHECKPOINT_SAVE_INTERVAL = "save_interval_steps"
CHECKPOINT_SAVE_INTERVAL_DEFAULT = 0
CHECKPOINT_KEEP_LAST_N = "keep_last_n"
CHECKPOINT_KEEP_LAST_N_DEFAULT = 0
CHECKPOINT_KEEP_EVERY_N_STEPS = "keep_every_n_steps"
CHECKPOINT_KEEP_EVERY_N_STEPS_DEFAULT = 0
CHECKPOINT_SAVE_ON_PREEMPTION = "save_on_preemption"
CHECKPOINT_SAVE_ON_PREEMPTION_DEFAULT = False


class ValidationMode:
    WARN = "WARN"
    IGNORE = "IGNORE"
    FAIL = "FAIL"


CHECKPOINT_TAG_VALIDATION_DEFAULT = ValidationMode.WARN
CHECKPOINT_TAG_VALIDATION_MODES = [
    ValidationMode.WARN,
    ValidationMode.IGNORE,
    ValidationMode.FAIL,
]

# ---------------------------------------------------------------------------
# Training-health sentinel block (runtime/sentinel.py)
# ---------------------------------------------------------------------------
TRAINING_HEALTH = "training_health"
TRAINING_HEALTH_ENABLED = "enabled"
TRAINING_HEALTH_ENABLED_DEFAULT = False
TRAINING_HEALTH_POLICY = "policy"
TRAINING_HEALTH_POLICY_DEFAULT = "warn"
TRAINING_HEALTH_LOSS_ZSCORE = "loss_zscore"
TRAINING_HEALTH_LOSS_ZSCORE_DEFAULT = 6.0
TRAINING_HEALTH_GRAD_NORM_ZSCORE = "grad_norm_zscore"
TRAINING_HEALTH_GRAD_NORM_ZSCORE_DEFAULT = 6.0
TRAINING_HEALTH_EMA_BETA = "ema_beta"
TRAINING_HEALTH_EMA_BETA_DEFAULT = 0.98
TRAINING_HEALTH_WARMUP_STEPS = "warmup_steps"
TRAINING_HEALTH_WARMUP_STEPS_DEFAULT = 20
TRAINING_HEALTH_ROLLBACK_AFTER = "rollback_after"
TRAINING_HEALTH_ROLLBACK_AFTER_DEFAULT = 2
TRAINING_HEALTH_ABORT_AFTER = "abort_after"
TRAINING_HEALTH_ABORT_AFTER_DEFAULT = 5
TRAINING_HEALTH_MAX_ROLLBACKS = "max_rollbacks"
TRAINING_HEALTH_MAX_ROLLBACKS_DEFAULT = 2
TRAINING_HEALTH_HANG_TIMEOUT = "hang_timeout_seconds"
TRAINING_HEALTH_HANG_TIMEOUT_DEFAULT = 0.0
TRAINING_HEALTH_FAULT_INJECTION = "fault_injection"

# fp16 block: consecutive overflow-skipped steps tolerated while the
# dynamic loss scale sits at min_loss_scale before erroring (0 = warn-only)
FP16_MIN_SCALE_PATIENCE = "min_scale_patience"
FP16_MIN_SCALE_PATIENCE_DEFAULT = 0

# ---------------------------------------------------------------------------
# Telemetry block (runtime/telemetry.py: span tracing, goodput + MFU
# accounting, trigger-driven profiler capture)
# ---------------------------------------------------------------------------
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False
TELEMETRY_GOODPUT = "goodput"
TELEMETRY_GOODPUT_DEFAULT = True
TELEMETRY_MFU = "mfu"
TELEMETRY_MFU_DEFAULT = True
TELEMETRY_SPANS = "spans"
TELEMETRY_SPANS_DEFAULT = True
TELEMETRY_TRACE_DIR = "trace_dir"
TELEMETRY_TRACE_DIR_DEFAULT = None
TELEMETRY_CAPTURE = "capture"             # {"start_step": N, "num_steps": M}
TELEMETRY_CAPTURE_START_STEP = "start_step"
TELEMETRY_CAPTURE_NUM_STEPS = "num_steps"
TELEMETRY_CAPTURE_NUM_STEPS_DEFAULT = 1
TELEMETRY_MEMORY_WATERMARK_INTERVAL = "memory_watermark_interval_steps"
TELEMETRY_MEMORY_WATERMARK_INTERVAL_DEFAULT = 0
TELEMETRY_CAPTURE_ON_ANOMALY = "capture_on_anomaly"
TELEMETRY_CAPTURE_ON_ANOMALY_DEFAULT = False
TELEMETRY_ANOMALY_CAPTURE_STEPS = "anomaly_capture_steps"
TELEMETRY_ANOMALY_CAPTURE_STEPS_DEFAULT = 1
# Fleet observability sub-block (runtime/fleet.py): cross-host scalar
# aggregation + merged Perfetto capture + collective-skew straggler probe.
TELEMETRY_FLEET = "fleet"
TELEMETRY_FLEET_ENABLED = "enabled"
TELEMETRY_FLEET_ENABLED_DEFAULT = False
TELEMETRY_FLEET_WINDOW_STEPS = "window_steps"
TELEMETRY_FLEET_WINDOW_STEPS_DEFAULT = 50
TELEMETRY_FLEET_SKEW_INTERVAL = "skew_interval_steps"   # 0 disables probe
TELEMETRY_FLEET_SKEW_INTERVAL_DEFAULT = 10
TELEMETRY_FLEET_SKEW_EMA_BETA = "skew_ema_beta"
TELEMETRY_FLEET_SKEW_EMA_BETA_DEFAULT = 0.9
TELEMETRY_FLEET_SKEW_THRESHOLD_MS = "skew_slow_threshold_ms"
TELEMETRY_FLEET_SKEW_THRESHOLD_MS_DEFAULT = 50.0
TELEMETRY_FLEET_MAX_TRACE_EVENTS = "max_trace_events"
TELEMETRY_FLEET_MAX_TRACE_EVENTS_DEFAULT = 2000

# ---------------------------------------------------------------------------
# MoE block (moe/layer.py, config-drivable via apply_ds_config)
# ---------------------------------------------------------------------------
MOE = "moe"
MOE_ENABLED = "enabled"
MOE_NUM_EXPERTS = "num_experts"
MOE_TOP_K = "top_k"
MOE_TOP_K_DEFAULT = 1
MOE_TOP_K_CHOICES = (1, 2)
MOE_CAPACITY_FACTOR = "capacity_factor"
MOE_CAPACITY_FACTOR_DEFAULT = 1.25
MOE_JITTER_EPS = "jitter_eps"
MOE_JITTER_EPS_DEFAULT = 0.0
MOE_AUX_LOSS_COEF = "aux_loss_coef"
MOE_AUX_LOSS_COEF_DEFAULT = 0.01
# 1 = global capacity (reference numerics); 0 opts in to auto-sized groups
MOE_NUM_GROUPS = "num_groups"
MOE_NUM_GROUPS_DEFAULT = 1
# dispatch engine: "einsum" = GShard one-hot [T, E, C] einsum pair
# (reference numerics); "sort" = argsort token permutation + Pallas
# grouped matmul (the fast path)
MOE_DISPATCH = "dispatch"
MOE_DISPATCH_DEFAULT = "einsum"
MOE_DISPATCH_MODES = ("einsum", "sort")
# expert-parallel all_to_all/compute software pipeline depth (sort engine)
MOE_A2A_OVERLAP_CHUNKS = "a2a_overlap_chunks"
MOE_A2A_OVERLAP_CHUNKS_DEFAULT = 1
# renormalize top-2 combine weights over capacity-surviving choices
MOE_RENORM_KEPT_CHOICES = "renorm_kept_choices"
MOE_RENORM_KEPT_CHOICES_DEFAULT = False
# Routing observability (Train/MoE/expert_load_* + capacity-drop fraction
# from the sort-dispatch path; requires dispatch="sort")
MOE_OBSERVABILITY = "observability"
MOE_OBSERVABILITY_DEFAULT = False

# ---------------------------------------------------------------------------
# Sparse attention block
# ---------------------------------------------------------------------------
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

# ---------------------------------------------------------------------------
# Packing block (document-packed ragged batches; runtime/packing.py)
# ---------------------------------------------------------------------------
PACKING = "packing"
PACKING_ENABLED = "enabled"
PACKING_ENABLED_DEFAULT = False
# token id written on pad positions (segment id 0 marks them for the
# kernels' masks and the effective-token accounting)
PACKING_PAD_ID = "pad_id"
PACKING_PAD_ID_DEFAULT = 0
# drop rows under 50% occupancy (bench hygiene for tail rows)
PACKING_DROP_TAIL = "drop_tail"
PACKING_DROP_TAIL_DEFAULT = False

# ---------------------------------------------------------------------------
# Pipeline block (config-driven 1F1B schedule; parallel/pipeline_spmd.py
# + parallel/schedule.py)
# ---------------------------------------------------------------------------
PIPELINE = "pipeline"
# number of pipeline stages (the `pipe` mesh axis size)
PIPELINE_STAGES = "stages"
# micro-batches per 1F1B batch; None = gradient_accumulation_steps when
# > 1, else the stage count (a full pipeline)
PIPELINE_MICRO_BATCHES = "micro_batches"
# software-pipeline the p2p ppermutes against stage compute (wire
# latency 2 — transfers hidden, fill/drain doubled; see
# parallel/schedule.bubble_fraction)
PIPELINE_COMM_OVERLAP = "comm_overlap"
PIPELINE_COMM_OVERLAP_DEFAULT = False

# ---------------------------------------------------------------------------
# Multislice block (slice-partitioned mesh over a DCN fabric;
# parallel/multislice.py + docs/multislice.md)
# ---------------------------------------------------------------------------
MULTISLICE = "multislice"
# number of named slices the mesh is partitioned into (>= 2)
MULTISLICE_SLICES = "slices"
# which mesh axis the slice boundary cuts: "pipe" maps contiguous
# pipeline-stage spans to slices (stage-boundary p2p crosses DCN);
# "data" splits the dp axis (the EF compressed reduce-scatter crosses)
MULTISLICE_AXIS = "axis"
MULTISLICE_AXIS_DEFAULT = "pipe"
MULTISLICE_AXIS_CHOICES = ("pipe", "data")
# optional slice names (len == slices, unique); default slice0..N-1
MULTISLICE_NAMES = "names"
# optional {slice name: [heartbeat peer names]} — the unit of staleness
# escalation; required for slice_kill faults and slice-loss survival
MULTISLICE_SLICE_PEERS = "slice_peers"
# DCN wire sub-block
MULTISLICE_DCN = "dcn"
# allow fp32 upcast on cross-slice hops (default: refuse — the DCN
# fabric is ~10x slower, doubling hop bytes there is a perf foot-gun)
MULTISLICE_DCN_FP32_COMM = "fp32_comm"
MULTISLICE_DCN_FP32_COMM_DEFAULT = False
# pack 8 signs/byte on the EF compressed wire (axis="data")
MULTISLICE_DCN_PACKED_WIRE = "packed_wire"
MULTISLICE_DCN_PACKED_WIRE_DEFAULT = True
# route cross-slice dp reduction over the EF sign-compressed wire
# (axis="data"; requires quantization.gradient_compression)
MULTISLICE_DCN_COMPRESS = "compress_dp_reduce"
MULTISLICE_DCN_COMPRESS_DEFAULT = True
# dead slice => in-process re-partition (SliceLostError) instead of a
# job-wide PeerFailureError kill
MULTISLICE_SURVIVE = "survive_slice_loss"
MULTISLICE_SURVIVE_DEFAULT = True

# ---------------------------------------------------------------------------
# Inference block (serving engine; deeperspeed_tpu/inference)
# ---------------------------------------------------------------------------
INFERENCE = "inference"
INFERENCE_ENABLED = "enabled"
INFERENCE_ENABLED_DEFAULT = False
# KV-cache page geometry: slots per page (128 = one lane tile on TPU)
# and pool pages per layer (page 0 is the reserved trash page)
INFERENCE_PAGE_SIZE = "page_size"
INFERENCE_PAGE_SIZE_DEFAULT = 128
INFERENCE_NUM_PAGES = "num_pages"
INFERENCE_NUM_PAGES_DEFAULT = 1024
# serving window; None = the model's max_seq_len
INFERENCE_MAX_SEQ_LEN = "max_seq_len"
INFERENCE_MAX_SEQ_LEN_DEFAULT = None
# in-flight decode sequences (the continuous batch)
INFERENCE_MAX_BATCH_SIZE = "max_batch_size"
INFERENCE_MAX_BATCH_SIZE_DEFAULT = 8
# per-step admission budget: a prefill costs its padded bucket length,
# a decode costs 1 (scheduler.py)
INFERENCE_TOKEN_BUDGET = "token_budget"
INFERENCE_TOKEN_BUDGET_DEFAULT = 4096
# compiled-shape bucket ladders (None = derived defaults)
INFERENCE_PREFILL_LENGTHS = "prefill_lengths"
INFERENCE_PREFILL_BATCH_SIZES = "prefill_batch_sizes"
INFERENCE_DECODE_BATCH_SIZES = "decode_batch_sizes"
# sampling: 0.0 = greedy argmax (deterministic)
INFERENCE_TEMPERATURE = "temperature"
INFERENCE_TEMPERATURE_DEFAULT = 0.0
INFERENCE_SEED = "seed"
INFERENCE_SEED_DEFAULT = 0
# decode-attention backend: auto (Pallas kernel on TPU, XLA elsewhere)
INFERENCE_KERNEL = "kernel"
INFERENCE_KERNEL_DEFAULT = "auto"
INFERENCE_KERNEL_CHOICES = ("auto", "pallas", "xla")
# KV-cache storage dtype: null = the params' compute dtype. Validated
# at parse time against the POOL dtypes the cache actually implements —
# any other resolve_precision spelling used to slip through and surface
# as a late kernel error far from the config.
INFERENCE_KV_DTYPE = "kv_cache_dtype"
INFERENCE_KV_DTYPE_DEFAULT = None
INFERENCE_KV_DTYPE_CHOICES = ("bfloat16", "bf16", "float16", "fp16",
                              "half", "float32", "fp32", "float", "int8")

# Graceful drain (SIGTERM): stop admissions, finish in-flight sequences
# for at most this many seconds, flush Serve/* telemetry, exit 0.
INFERENCE_DRAIN_DEADLINE = "drain_deadline_s"
INFERENCE_DRAIN_DEADLINE_DEFAULT = 30.0

# -- serving robustness (inference/admission.py; docs/inference.md
#    "Serving under failure") ------------------------------------------------

# priority class a submit() without an explicit `priority` gets
INFERENCE_DEFAULT_PRIORITY = "default_priority"
INFERENCE_DEFAULT_PRIORITY_DEFAULT = "interactive"

# hang watchdog around the serving step (PR 4 machinery): 0 = off
INFERENCE_HANG_TIMEOUT = "hang_timeout_s"
INFERENCE_HANG_TIMEOUT_DEFAULT = 0.0

# admission control / load shedding sub-block (absent => no shedding,
# the pre-robustness unbounded-queue behavior)
INFERENCE_ADMISSION = "admission"
INFERENCE_ADMISSION_ENABLED = "enabled"
INFERENCE_ADMISSION_ENABLED_DEFAULT = True
INFERENCE_ADMISSION_MAX_QUEUE_DEPTH = "max_queue_depth"
INFERENCE_ADMISSION_MAX_QUEUE_DEPTH_DEFAULT = 256
INFERENCE_ADMISSION_SHED_POOL_UTIL = "shed_page_pool_util"
INFERENCE_ADMISSION_SHED_POOL_UTIL_DEFAULT = 0.95
INFERENCE_ADMISSION_SHED_TTFT_EMA = "shed_ttft_ema_ms"
INFERENCE_ADMISSION_SHED_TTFT_EMA_DEFAULT = None
INFERENCE_ADMISSION_TTFT_EMA_BETA = "ttft_ema_beta"
INFERENCE_ADMISSION_TTFT_EMA_BETA_DEFAULT = 0.9
INFERENCE_ADMISSION_RETRY_AFTER_CAP = "retry_after_cap_s"
INFERENCE_ADMISSION_RETRY_AFTER_CAP_DEFAULT = 60.0

# step-failure retry/poison sub-block (always active; the defaults
# apply when the block is absent)
INFERENCE_RETRY = "retry"
INFERENCE_RETRY_MAX_ATTEMPTS = "max_attempts"
INFERENCE_RETRY_MAX_ATTEMPTS_DEFAULT = 3
INFERENCE_RETRY_BACKOFF_BASE = "backoff_base_ms"
INFERENCE_RETRY_BACKOFF_BASE_DEFAULT = 50.0
INFERENCE_RETRY_BACKOFF_CAP = "backoff_cap_ms"
INFERENCE_RETRY_BACKOFF_CAP_DEFAULT = 2000.0
INFERENCE_RETRY_JITTER = "jitter"
INFERENCE_RETRY_JITTER_DEFAULT = 0.25

# serving fault injection (runtime/fault_injection.py serving kinds);
# same schema as training_health.fault_injection
INFERENCE_FAULT_INJECTION = "fault_injection"

# prefix/radix KV-cache reuse sub-block (inference/kv_cache.PrefixCache):
# shared-prompt prefills attach registered page chains by refcount
INFERENCE_PREFIX_CACHE = "prefix_cache"
INFERENCE_PREFIX_CACHE_ENABLED = "enabled"
INFERENCE_PREFIX_CACHE_ENABLED_DEFAULT = False
# registry size cap in pages (null = bounded only by pool pressure:
# allocation shortfalls reclaim LRU unshared registry pages)
INFERENCE_PREFIX_CACHE_MAX_PAGES = "max_pages"
INFERENCE_PREFIX_CACHE_MAX_PAGES_DEFAULT = None

# speculative decoding sub-block: a draft model proposes
# num_draft_tokens per decode step; the target verifies the window in
# one batched forward (engine arg `draft_model` supplies the drafter)
INFERENCE_SPECULATIVE = "speculative"
INFERENCE_SPECULATIVE_ENABLED = "enabled"
INFERENCE_SPECULATIVE_ENABLED_DEFAULT = False
INFERENCE_SPECULATIVE_NUM_DRAFT = "num_draft_tokens"
INFERENCE_SPECULATIVE_NUM_DRAFT_DEFAULT = 4
# int8 weight-only quantization for the DRAFT params (the draft step is
# weight-bandwidth bound too); null = the target's compute dtype
INFERENCE_SPECULATIVE_DRAFT_WEIGHT_QUANT = "draft_weight_quant"
INFERENCE_SPECULATIVE_DRAFT_WEIGHT_QUANT_DEFAULT = None

# disaggregated prefill/decode serving sub-block (docs/inference.md
# "Disaggregated prefill/decode"): an engine's pool role — a prefill
# pool runs admission + prefill and hands completed requests' KV pages
# to a decode pool over the coordination-service transport; "unified"
# (the default) is the single-engine behavior
INFERENCE_DISAGGREGATION = "disaggregation"
INFERENCE_DISAGG_ROLE = "role"
INFERENCE_DISAGG_ROLE_DEFAULT = "unified"
INFERENCE_DISAGG_ROLE_CHOICES = ("unified", "prefill", "decode")
# pool identity: the handoff transport key namespace AND the
# role/host labels on the Serve/* Prometheus families (null = derived
# from the role, e.g. "prefill-0")
INFERENCE_DISAGG_POOL_ID = "pool_id"
INFERENCE_DISAGG_POOL_ID_DEFAULT = None
# an offer the decode side has not acked within this window is treated
# as rejected: pages return to the prefill pool's free list and the
# request requeues for a fresh prefill + re-offer
INFERENCE_DISAGG_HANDOFF_TIMEOUT = "handoff_timeout_s"
INFERENCE_DISAGG_HANDOFF_TIMEOUT_DEFAULT = 30.0

# front-end SLO router sub-block (inference/router.py): weighted
# least-load admission across pools on the queue-depth / page-pool /
# TTFT-EMA gauges the admission controller already maintains
INFERENCE_ROUTER = "router"
INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT = "queue_depth_weight"
INFERENCE_ROUTER_QUEUE_DEPTH_WEIGHT_DEFAULT = 1.0
INFERENCE_ROUTER_POOL_UTIL_WEIGHT = "pool_util_weight"
INFERENCE_ROUTER_POOL_UTIL_WEIGHT_DEFAULT = 32.0
INFERENCE_ROUTER_TTFT_WEIGHT = "ttft_weight"
INFERENCE_ROUTER_TTFT_WEIGHT_DEFAULT = 0.01
# advisory autoscaling threshold: when every routable pool's page-pool
# utilization sits above this, Serve/router/advise_scale_up goes to 1
INFERENCE_ROUTER_SCALE_UP_UTIL = "scale_up_util"
INFERENCE_ROUTER_SCALE_UP_UTIL_DEFAULT = 0.85

# ---------------------------------------------------------------------------
# Profile-guided schedule planner (docs/planner.md): the engine-side
# hook consuming a persisted `ds_plan` plan file — its resolved config
# (zero_optimization.schedule, activation checkpointing, offload tier,
# quantization recipe) merges UNDER the user's explicit keys
# ---------------------------------------------------------------------------
PLANNER = "planner"
PLANNER_ENABLED = "enabled"
PLANNER_ENABLED_DEFAULT = True
PLANNER_PLAN_FILE = "plan_file"
PLANNER_PLAN_FILE_DEFAULT = None
PLANNER_STRICT_DEVICE_MATCH = "strict_device_match"
PLANNER_STRICT_DEVICE_MATCH_DEFAULT = False

# ---------------------------------------------------------------------------
# Quantization (docs/quantization.md): low-precision hot paths — serving
# int8 weights, delayed-scaling fp8/int8 FFN matmuls, compressed
# cross-host gradients on the explicit ZeRO-3 schedule
# ---------------------------------------------------------------------------
QUANTIZATION = "quantization"
QUANTIZATION_ENABLED = "enabled"
QUANTIZATION_ENABLED_DEFAULT = True
# serving weight-only quantization (module_inject.prepare_inference_params)
QUANTIZATION_WEIGHTS = "weights"
QUANTIZATION_WEIGHTS_DEFAULT = None
QUANTIZATION_WEIGHTS_CHOICES = ("int8",)
# delayed-scaling quantized FFN (training; ops/pallas/quant_matmul)
QUANTIZATION_FFN = "ffn"
QUANTIZATION_FFN_RECIPE = "recipe"
QUANTIZATION_FFN_RECIPE_CHOICES = ("int8", "fp8")
QUANTIZATION_FFN_HISTORY = "amax_history_len"
QUANTIZATION_FFN_HISTORY_DEFAULT = 16
QUANTIZATION_FFN_MARGIN = "margin"
QUANTIZATION_FFN_MARGIN_DEFAULT = 1.0
# error-feedback compressed gradients on the cross-host DP axis of the
# explicit ZeRO-3 schedule (runtime/comm/compressed.py)
QUANTIZATION_GRAD_COMPRESSION = "gradient_compression"
QUANTIZATION_GRAD_COMPRESSION_ENABLED = "enabled"
QUANTIZATION_GRAD_COMPRESSION_ENABLED_DEFAULT = True
# pack 8 signs/byte on the compressed wire (8x fewer DCN bytes; same
# quantization law, bit-exact EF state — runtime/comm/compressed.py)
QUANTIZATION_GRAD_COMPRESSION_PACKED = "packed_wire"
QUANTIZATION_GRAD_COMPRESSION_PACKED_DEFAULT = False

# ---------------------------------------------------------------------------
# Online RL (docs/rl.md): the co-located train+serve driver
# (deeperspeed_tpu/rl) — rollout generation through the serving engine,
# PPO-clip / DPO losses on the training engine, train→serve weight flow
# by in-process hot-swap with zero recompiles
# ---------------------------------------------------------------------------
RL = "rl"
RL_ENABLED = "enabled"
RL_ENABLED_DEFAULT = False
RL_LOSS = "loss"
RL_LOSS_DEFAULT = "ppo_clip"
RL_LOSS_CHOICES = ("ppo_clip", "dpo")
# total rollouts generated per driver iteration (must be a multiple of
# group_size; PPO updates on all of them, DPO on one pair per group)
RL_ROLLOUTS_PER_ITERATION = "rollouts_per_iteration"
RL_ROLLOUTS_PER_ITERATION_DEFAULT = 8
# rollouts sampled per prompt: the advantage baseline group (PPO) /
# the chosen-vs-rejected candidate pool (DPO, needs >= 2)
RL_GROUP_SIZE = "group_size"
RL_GROUP_SIZE_DEFAULT = 1
RL_MAX_NEW_TOKENS = "max_new_tokens"
RL_MAX_NEW_TOKENS_DEFAULT = 16
# fixed padded rollout width (the ONE compiled train/logprob shape);
# null = max prompt length + max_new_tokens, rounded up to 8
RL_SEQUENCE_LENGTH = "sequence_length"
RL_SEQUENCE_LENGTH_DEFAULT = None
# PPO-clip knobs
RL_CLIP_RATIO = "clip_ratio"
RL_CLIP_RATIO_DEFAULT = 0.2
RL_KL_COEF = "kl_coef"
RL_KL_COEF_DEFAULT = 0.05
# DPO preference temperature
RL_BETA = "beta"
RL_BETA_DEFAULT = 0.1
# driver iterations between committed checkpoints (the deterministic-
# resume granularity: a kill replays at most this many iterations)
RL_CHECKPOINT_INTERVAL = "checkpoint_interval"
RL_CHECKPOINT_INTERVAL_DEFAULT = 1
