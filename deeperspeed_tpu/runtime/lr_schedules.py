"""LR schedules (reference: `deepspeed/runtime/lr_schedules.py`).

Four schedules with the reference's exact math and stateful API
(`step`/`get_lr`/`get_last_lr`/`state_dict`/`load_state_dict`):
``LRRangeTest``, ``OneCycle``, ``WarmupLR``, ``WarmupDecayLR``.

Two ways to consume them:

- Stateful, host-side: construct with an optimizer exposing torch-style
  ``param_groups`` (our optimizer wrappers do) and call ``step()`` per batch.
- Pure, jit-side: every class has ``lr_at(iteration)`` (list of group lrs)
  and module-level ``make_schedule_fn(name, params)`` returns a scalar
  ``f(step) -> lr`` suitable for optax inside a jitted train step.
"""

import math

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

EDGE_VALUE = "edge_value"
MID_VALUE = "mid_value"

CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"

CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"
CYCLE_MOMENTUM = "cycle_momentum"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"

TOTAL_NUM_STEPS = "total_num_steps"


def _require_param_groups(optimizer):
    """Accept any optimizer wrapper exposing torch-style `param_groups`."""
    if hasattr(optimizer, "param_groups"):
        return optimizer
    inner = getattr(optimizer, "optimizer", None)
    if inner is not None and hasattr(inner, "param_groups"):
        return inner
    raise TypeError(
        f"{type(optimizer).__name__} does not expose param_groups")


def _format_param(optimizer, value, name):
    if isinstance(value, (list, tuple)):
        if len(value) != len(optimizer.param_groups):
            raise ValueError(
                f"expected {len(optimizer.param_groups)} values for {name}, "
                f"got {len(value)}")
        return list(value)
    return [value] * len(optimizer.param_groups)


class _LRScheduler:
    """Shared stepping/state plumbing; subclasses implement lr_at()."""

    def __init__(self, optimizer, last_batch_iteration=-1):
        self.optimizer = _require_param_groups(optimizer)
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, iteration):
        raise NotImplementedError

    def get_lr(self):
        return self.lr_at(self.last_batch_iteration)

    def get_last_lr(self):
        if getattr(self, "_last_lr", None) is None:
            raise RuntimeError("need to call step() first")
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        for param_group, lr in zip(self.optimizer.param_groups,
                                   self.get_lr()):
            param_group["lr"] = lr
        self._last_lr = [g["lr"] for g in self.optimizer.param_groups]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_LRScheduler):
    """LR range test: grow lr from a base with constant frequency
    (arXiv:1803.09820); used to find the divergence boundary."""

    def __init__(self, optimizer, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = _format_param(self.optimizer, lr_range_test_min_lr,
                                    "lr_range_test_min_lr")
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        if last_batch_iteration == -1:
            for group, lr in zip(self.optimizer.param_groups, self.min_lr):
                group["lr"] = lr

    def _interval(self, iteration):
        frac = float(iteration + 1) / self.step_size
        return math.floor(frac) if self.staircase else frac

    def lr_at(self, iteration):
        increase = 1 + self.step_rate * self._interval(iteration)
        return [lr * increase for lr in self.min_lr]


class OneCycle(_LRScheduler):
    """1Cycle policy: one lr (and inverse momentum) cycle followed by decay
    (arXiv:1803.09820)."""

    def __init__(self, optimizer, cycle_min_lr, cycle_max_lr,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.8, cycle_max_mom=0.9,
                 decay_mom_rate=0.0, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)

        first = float(cycle_first_step_size)
        second = float(cycle_second_step_size
                       if cycle_second_step_size is not None else first)
        self.total_size = first + second
        self.step_ratio = first / self.total_size
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = (cycle_first_stair_count
                                   if cycle_second_stair_count is None else
                                   cycle_second_stair_count)
        self.decay_step_size = decay_step_size

        self.min_lrs = [cycle_min_lr] * len(self.optimizer.param_groups)
        self.max_lrs = [cycle_max_lr] * len(self.optimizer.param_groups)
        self.decay_lr_rate = decay_lr_rate
        if last_batch_iteration == -1:
            for lr, group in zip(self.min_lrs, self.optimizer.param_groups):
                group["lr"] = lr

        self.cycle_momentum = cycle_momentum
        if cycle_momentum:
            has_betas = any("betas" in g for g in self.optimizer.param_groups) \
                or "betas" in getattr(self.optimizer, "defaults", {})
            if not has_betas:
                self.cycle_momentum = False
            else:
                self.decay_mom_rate = decay_mom_rate
                n = len(self.optimizer.param_groups)
                self.min_moms = [(cycle_min_mom, 0.99)] * n
                self.max_moms = [(cycle_max_mom, 0.99)] * n
                if last_batch_iteration == -1:
                    for mom, group in zip(self.min_moms,
                                          self.optimizer.param_groups):
                        group["betas"] = mom

    def _scale_factor(self, iteration):
        batch_iteration = iteration + 1
        cycle = math.floor(1 + batch_iteration / self.total_size)
        x = 1.0 + batch_iteration / self.total_size - cycle
        if x <= self.step_ratio:
            return x / self.step_ratio
        return (x - 1) / (self.step_ratio - 1)

    def lr_at(self, iteration):
        if iteration < self.total_size:
            scale = self._scale_factor(iteration)
            return [min_lr + (max_lr - min_lr) * scale
                    for min_lr, max_lr in zip(self.min_lrs, self.max_lrs)]
        decay_iter = iteration - self.total_size + 1
        factor = 1 + self.decay_lr_rate * decay_iter / self.decay_step_size
        return [min_lr / factor for min_lr in self.min_lrs]

    def mom_at(self, iteration):
        if not self.cycle_momentum:
            return None
        if iteration < self.total_size:
            scale = self._scale_factor(iteration)
            return [(max_b[0] - (max_b[0] - min_b[0]) * scale, min_b[1])
                    for min_b, max_b in zip(self.min_moms, self.max_moms)]
        decay_iter = iteration - self.total_size + 1
        factor = 1 + self.decay_mom_rate * decay_iter / self.decay_step_size
        return [(b0 * factor, b1) for b0, b1 in self.max_moms]

    def get_mom(self):
        return self.mom_at(self.last_batch_iteration)

    def step(self, batch_iteration=None):
        super().step(batch_iteration)
        if self.cycle_momentum:
            for group, momentum in zip(self.optimizer.param_groups,
                                       self.get_mom()):
                group["betas"] = momentum


class WarmupLR(_LRScheduler):
    """Log-ramp lr from min to max over warmup_num_steps, then hold."""

    def __init__(self, optimizer, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lrs = _format_param(self.optimizer, warmup_min_lr, "min_lr")
        self.max_lrs = _format_param(self.optimizer, warmup_max_lr, "max_lr")
        self.delta_lrs = [big - small
                          for big, small in zip(self.max_lrs, self.min_lrs)]
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _gamma(self, iteration):
        if iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(iteration + 1)
        return 1.0

    def lr_at(self, iteration):
        if iteration < 0:
            return [0.0]
        gamma = self._gamma(iteration)
        return [min_lr + delta * gamma
                for min_lr, delta in zip(self.min_lrs, self.delta_lrs)]


class WarmupDecayLR(WarmupLR):
    """WarmupLR followed by linear decay to zero at total_num_steps."""

    def __init__(self, optimizer, total_num_steps, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000,
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, last_batch_iteration)

    def _gamma(self, iteration):
        if iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(iteration + 1)
        return max(
            0.0,
            float(self.total_num_steps - iteration) /
            float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


_SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_scheduler_class(name):
    if name not in _SCHEDULE_CLASSES:
        raise ValueError(
            f"unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULE_CLASSES[name]


class _DummyGroups:
    """Stand-in optimizer so schedules can be evaluated as pure functions."""
    param_groups = None

    def __init__(self):
        self.param_groups = [{"lr": 0.0, "betas": (0.9, 0.999)}]
        self.defaults = {"betas": (0.9, 0.999)}


def make_schedule_fn(name, params):
    """Return a pure ``f(step: int) -> float`` for jit-side lr computation
    (optax-style). `step` counts optimizer steps from 0."""
    sched = get_scheduler_class(name)(_DummyGroups(), **params)

    def schedule(step):
        return sched.lr_at(int(step))[0]

    return schedule


def add_tuning_arguments(parser):
    """Reference CLI tuning args (`lr_schedules.py:54`)."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None)
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_momentum", default=False,
                       action="store_true")
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0.0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    return parser
