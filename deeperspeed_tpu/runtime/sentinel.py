"""Training-health sentinel: in-band anomaly detection + automatic recovery.

Multi-week runs die in ways the reference fork only handles reactively —
loss blow-ups, stuck loss scales, corrupted batches, hung hosts. This
module closes the loop:

- **Device-side probe** (`probe_update`): a handful of scalar ops fused
  into the existing jitted train step. It reuses the global grad norm and
  overflow flag the step already computes (`engine._apply_update`) and
  flags non-finite loss/grads plus EMA z-score spikes in loss and
  grad-norm. Debiased EMA mean/variance are carried in `HealthState`
  (part of `EngineState`), so detection costs no extra passes over the
  gradient tree and no host round-trips beyond the one scalar flags read.
- **In-jit quarantine**: with policy `skip_batch` or higher, a flagged
  step's optimizer update is skipped branchlessly (the same select
  machinery as the fp16 overflow skip) — a NaN gradient can never reach
  the master weights, even in bf16 runs with no loss-scale machinery.
- **Host-side escalation** (`TrainingHealthSentinel.after_step`):
  `warn` -> `skip_batch` (quarantine + dataloader provenance epoch/offset)
  -> `rollback` (restore the last committed checkpoint via the
  `AsyncCheckpointManager`, keep the dataloader past the bad window)
  -> `abort` (raise `TrainingHealthError`) after K consecutive anomalies.
- **Hang watchdog** (`HangWatchdog`): a per-step wall-clock deadline armed
  around every `train_batch`; on expiry it dumps all-thread stacks and
  triggers the existing preemption-style emergency save.

Everything is driven by the validated ``"training_health"`` JSON block
(`runtime/config.py`); the subsystem is entirely absent from the compiled
program when disabled. `runtime/fault_injection.py` drives every path
deterministically for tests and the `DS_BENCH_SENTINEL=1` bench row.
"""

import threading
import time
import traceback
import weakref
from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist, logger

# anomaly bitmask (HealthState.flags)
ANOM_NONFINITE_LOSS = 1
ANOM_NONFINITE_GRAD = 2
ANOM_LOSS_SPIKE = 4
ANOM_GRAD_SPIKE = 8

FLAG_NAMES = {
    ANOM_NONFINITE_LOSS: "nonfinite_loss",
    ANOM_NONFINITE_GRAD: "nonfinite_grad",
    ANOM_LOSS_SPIKE: "loss_spike",
    ANOM_GRAD_SPIKE: "grad_norm_spike",
}

# escalation ladder; the configured `policy` is the HIGHEST rung allowed
POLICIES = ("warn", "skip_batch", "rollback", "abort")


class TrainingHealthError(RuntimeError):
    """Raised when the sentinel escalates to `abort` (or a rollback is
    requested but impossible): the run is sick beyond automatic repair."""


class HealthState(NamedTuple):
    """Device-resident probe state, carried through the jitted step.

    EMAs are stored un-debiased (`ema / (1 - beta^count)` is the mean);
    `count` only advances on healthy steps so anomalies never poison the
    statistics they are measured against."""
    loss_ema: jnp.ndarray      # f32: EMA of loss
    loss_sq_ema: jnp.ndarray   # f32: EMA of loss^2
    gnorm_ema: jnp.ndarray     # f32: EMA of grad norm
    gnorm_sq_ema: jnp.ndarray  # f32: EMA of grad norm^2
    count: jnp.ndarray         # i32: healthy samples incorporated
    flags: jnp.ndarray         # i32: bitmask for the LAST step
    anomalies: jnp.ndarray     # i32: cumulative anomalous steps
    quarantined: jnp.ndarray   # i32: cumulative in-jit skipped updates


class ProbeConfig(NamedTuple):
    """Static (trace-time) probe knobs from the training_health block."""
    loss_zscore: float
    grad_norm_zscore: float
    ema_beta: float
    warmup_steps: int
    quarantine: bool    # policy >= skip_batch: hard anomalies skip in-jit


def init_health_state():
    # distinct arrays per field: the engine DONATES its state pytree to
    # the jitted step, and a buffer appearing twice in a donated tree is
    # an XLA error ("attempt to donate the same buffer twice")
    def z32():
        return jnp.array(0.0, jnp.float32)

    def i32():
        return jnp.array(0, jnp.int32)

    return HealthState(loss_ema=z32(), loss_sq_ema=z32(), gnorm_ema=z32(),
                       gnorm_sq_ema=z32(), count=i32(), flags=i32(),
                       anomalies=i32(), quarantined=i32())


def _zscore(value, ema, sq_ema, count, beta):
    """Debiased EMA z-score; robust to the flat-metric case (var -> 0).

    The sd gets a floor of 2% of the mean: right after warmup the EMA
    variance is built from few samples and can be arbitrarily small, so
    a raw z-score flags ordinary jitter (measured: two near-equal losses
    put a 10% wiggle at z ~ 7.7). With the floor, a z of 6 requires a
    deviation of at least ~12% of the running mean — noise never clears
    it, while real blow-ups (orders of magnitude) always do."""
    n = jnp.maximum(count, 1).astype(jnp.float32)
    corr = 1.0 - jnp.power(jnp.float32(beta), n)
    mean = ema / corr
    var = jnp.maximum(sq_ema / corr - mean * mean, 0.0)
    sd = jnp.sqrt(var) + 0.02 * jnp.abs(mean) + 1e-12
    return (value - mean) / sd


def probe_update(health, loss, grad_norm, bad_grad, cfg):
    """One probe step: (new HealthState, hard-anomaly bool).

    Pure jnp scalar math — traced inside the jitted train step on the
    standard path, or run eagerly by the sentinel for host-optimizer
    tiers. `loss` may be None (update-only paths).

    `bad_grad` is the CALLER's non-finite-gradient verdict (may be a
    static Python False). The caller owns it because the right condition
    is precision-dependent: for bf16/fp32 runs it is `~isfinite(norm)`
    (no other machinery catches a NaN there), while for fp16 loss-scaled
    runs an overflow is a ROUTINE, self-correcting event during the
    scale search — it only becomes an anomaly once the scaler is pinned
    at its floor (see `grad_anomaly_in_jit`). Treating every overflow as
    an anomaly would escalate a healthy run to rollback/abort during the
    first dozen startup steps.
    """
    gn = jnp.asarray(grad_norm, jnp.float32)
    gn_finite = jnp.isfinite(gn)
    flags = jnp.where(jnp.asarray(bad_grad, jnp.bool_),
                      ANOM_NONFINITE_GRAD, 0).astype(jnp.int32)

    warm = health.count >= cfg.warmup_steps
    if cfg.grad_norm_zscore > 0:
        gz = _zscore(gn, health.gnorm_ema, health.gnorm_sq_ema,
                     health.count, cfg.ema_beta)
        g_spike = jnp.logical_and(jnp.logical_and(warm, gn_finite),
                                  gz > cfg.grad_norm_zscore)
        flags = flags | jnp.where(g_spike, ANOM_GRAD_SPIKE, 0)

    if loss is not None:
        ls = jnp.asarray(loss, jnp.float32)
        l_finite = jnp.isfinite(ls)
        flags = flags | jnp.where(l_finite, 0, ANOM_NONFINITE_LOSS)
        if cfg.loss_zscore > 0:
            lz = _zscore(ls, health.loss_ema, health.loss_sq_ema,
                         health.count, cfg.ema_beta)
            l_spike = jnp.logical_and(jnp.logical_and(warm, l_finite),
                                      lz > cfg.loss_zscore)
            flags = flags | jnp.where(l_spike, ANOM_LOSS_SPIKE, 0)

    anomalous = flags != 0
    hard = jnp.logical_and(anomalous, cfg.quarantine)

    beta = jnp.float32(cfg.ema_beta)

    def ema(prev, value):
        # frozen on anomalous steps: a spike must not drag the baseline
        # toward itself (the next spike would then look normal)
        value = jnp.where(jnp.isfinite(value), value, prev)
        return jnp.where(anomalous, prev, beta * prev + (1 - beta) * value)

    new = HealthState(
        loss_ema=(ema(health.loss_ema, jnp.asarray(loss, jnp.float32))
                  if loss is not None else health.loss_ema),
        loss_sq_ema=(ema(health.loss_sq_ema,
                         jnp.square(jnp.asarray(loss, jnp.float32)))
                     if loss is not None else health.loss_sq_ema),
        gnorm_ema=ema(health.gnorm_ema, gn),
        gnorm_sq_ema=ema(health.gnorm_sq_ema, jnp.square(gn)),
        count=health.count + jnp.where(anomalous, 0, 1).astype(jnp.int32),
        flags=flags,
        anomalies=health.anomalies +
        jnp.where(anomalous, 1, 0).astype(jnp.int32),
        quarantined=health.quarantined +
        jnp.where(hard, 1, 0).astype(jnp.int32))
    return new, hard


def grad_anomaly_in_jit(engine, scale_state, grad_norm, overflow):
    """The `bad_grad` input for `probe_update` on the jitted path.

    - loss-scaled (fp16): overflow steps are the dynamic scaler's normal
      startup search and already skip their update; they count as an
      anomaly only once the scale is pinned at `min_loss_scale` (no room
      left to self-correct — the run is genuinely sick). The non-finite
      norm on such steps is the overflow itself, so the norm check is
      NOT applied separately.
    - unscaled (bf16/fp32): `overflow` is statically False and nothing
      else catches a NaN — a non-finite global norm IS the anomaly.
    """
    if engine._config.loss_scaling_enabled:
        if not engine.dynamic_loss_scale():
            # static scale: nothing self-corrects — overflow IS sickness
            return jnp.asarray(overflow, jnp.bool_)
        args = engine._config.dynamic_loss_scale_args or {}
        min_scale = float(args.get("min_loss_scale", 1))
        at_floor = scale_state.cur_scale <= min_scale
        return jnp.logical_and(jnp.asarray(overflow, jnp.bool_), at_floor)
    return jnp.logical_not(jnp.isfinite(
        jnp.asarray(grad_norm, jnp.float32)))


def decode_flags(flags):
    """Human-readable anomaly names for a flags bitmask."""
    return [name for bit, name in FLAG_NAMES.items() if flags & bit]


def dump_all_stacks():
    """Format every thread's current Python stack (watchdog expiry)."""
    import sys
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out)


class HangWatchdog:
    """Per-step wall-clock deadline on a daemon thread.

    `arm()` at step entry, `feed()` after the step's host work completes.
    On expiry the callback fires ONCE per armed window (a genuinely hung
    step must not spam a dump per poll tick). The thread holds only a
    weakref to its owner so discarded engines stay collectible; it exits
    when the owner does.

    Two owners share it: the training sentinel (`TrainingHealthSentinel`,
    `training_health.hang_timeout_seconds`) and the serving engine
    (`InferenceEngine._on_serving_hang`, `inference.hang_timeout_s` —
    expiry there requests a drain-style emergency flush instead of an
    emergency checkpoint). Both skip arming while the step's program is
    still compiling: an XLA compile is not a hang."""

    def __init__(self, timeout_s, owner, on_expire_name):
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._deadline = None
        self._fired = False
        self._stop = threading.Event()
        owner_ref = weakref.ref(owner)
        poll = max(min(self.timeout_s / 4.0, 1.0), 0.02)

        def loop():
            while not self._stop.wait(poll):
                owner = owner_ref()
                if owner is None:
                    return
                with self._lock:
                    expired = (self._deadline is not None
                               and not self._fired
                               and time.monotonic() > self._deadline)
                    if expired:
                        # one-shot per armed step: consume the deadline
                        # so the (slow) callback can't race a re-check —
                        # only the next arm() re-enables expiry
                        self._fired = True
                        self._deadline = None
                if expired:
                    try:
                        getattr(owner, on_expire_name)()
                    except Exception as e:  # pragma: no cover
                        logger.error(f"hang watchdog callback failed: {e}")
                del owner

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ds-hang-watchdog")
        self._thread.start()

    def arm(self):
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s
            self._fired = False

    def feed(self):
        with self._lock:
            self._deadline = None
            self._fired = False

    def stop(self):
        self._stop.set()


class TrainingHealthSentinel:
    """Host-side policy engine over the device probe's verdicts.

    Owned by the engine (constructed from the "training_health" config
    block); holds only a weakref back so the engine stays collectible."""

    def __init__(self, engine, policy="warn", loss_zscore=6.0,
                 grad_norm_zscore=6.0, ema_beta=0.98, warmup_steps=20,
                 rollback_after=2, abort_after=5, max_rollbacks=2,
                 hang_timeout_seconds=0.0, max_quarantine_records=64):
        self.policy = policy
        self.policy_rank = POLICIES.index(policy)
        self.rollback_after = int(rollback_after)
        self.abort_after = int(abort_after)
        self.max_rollbacks = int(max_rollbacks)
        self.max_quarantine_records = int(max_quarantine_records)
        self._engine_ref = weakref.ref(engine)

        # Host-optimizer tiers (ZeRO-Offload / param streaming) apply the
        # update on the host — no jitted update to fuse the probe into.
        # The sentinel then probes eagerly from the (already host-side)
        # step metrics; quarantine degrades to the tiers' own non-finite
        # skip, while rollback/abort still fully work.
        self.device_probe = not (getattr(engine, "host_offload", False)
                                 or getattr(engine, "param_offload", False))
        self.probe_config = ProbeConfig(
            loss_zscore=float(loss_zscore),
            grad_norm_zscore=float(grad_norm_zscore),
            ema_beta=float(ema_beta),
            warmup_steps=int(warmup_steps),
            quarantine=(self.policy_rank >= POLICIES.index("skip_batch")
                        and self.device_probe))
        self._host_health = None if self.device_probe else \
            init_health_state()

        # host-side mirrors / telemetry
        self.anomalies = 0
        self.quarantined = 0
        self.consecutive = 0
        self.rollbacks = 0
        self.quarantined_windows = []   # provenance records
        self.last_flags = 0
        self.watchdog_fires = 0
        self.last_stack_dump = None
        self._warned = 0

        self.watchdog = None
        if hang_timeout_seconds and hang_timeout_seconds > 0:
            self.watchdog = HangWatchdog(hang_timeout_seconds, self,
                                         "_on_hang")

    # ------------------------------------------------------------------
    # watchdog plumbing (called by the engine around every step)
    # ------------------------------------------------------------------

    def watchdog_arm(self):
        if self.watchdog is not None:
            self.watchdog.arm()

    def watchdog_feed(self):
        if self.watchdog is not None:
            self.watchdog.feed()

    def _on_hang(self):
        """Runs on the watchdog thread: the armed step blew its deadline."""
        self.watchdog_fires += 1
        dump = dump_all_stacks()
        self.last_stack_dump = dump
        logger.error(
            f"hang watchdog: step exceeded the "
            f"{self.watchdog.timeout_s:.1f}s deadline; all-thread stack "
            f"dump follows\n{dump}")
        engine = self._engine_ref()
        if engine is None:
            return
        # local hang vs peer failure: a step wedged inside a collective
        # because a PEER died looks identical from this host's stacks —
        # the heartbeat monitor (elasticity/heartbeat.py) disambiguates.
        # Stale peers -> name them (the supervisor/operator should look
        # THERE); all peers healthy -> this really is a local hang.
        peer_monitor = getattr(engine, "peer_monitor", None)
        if peer_monitor is not None:
            stale = [name for name, st in
                     peer_monitor.peer_status().items()
                     if st["status"] != "ok"]
            # cite the fleet skew probe's quantitative per-host verdict
            # when one exists (runtime/fleet.py note_skew): the
            # LOCAL-vs-peer call is then backed by measured ms/step
            skew_fn = getattr(peer_monitor, "skew_context", None)
            cites = []
            if skew_fn is not None:
                cites = [c for c in (skew_fn(n) for n in sorted(stale))
                         if c]
            if stale:
                logger.error(
                    f"hang watchdog: peer(s) {sorted(stale)} have stale "
                    f"heartbeats — this step is most likely blocked on a "
                    f"DEAD/SLOW PEER inside a collective, not hung "
                    f"locally (peer-failure escalation will fire at "
                    f"fail_after_s)"
                    + (f" [fleet skew probe: {'; '.join(cites)}]"
                       if cites else ""))
            else:
                logger.error(
                    "hang watchdog: all peer heartbeats are fresh — "
                    "treating this as a LOCAL hang")
        # memory snapshot now (host-side reads are thread-safe); a trace
        # is armed for the next step in case the hang clears
        self._telemetry_anomaly(engine, "watchdog_hang")
        manager = getattr(engine, "checkpoint_manager", None)
        if manager is not None and manager.save_on_preemption and \
                manager.save_dir:
            # preemption-style: flag only; the emergency save runs on the
            # main thread at the next step boundary (if the hang clears)
            manager.preemption_requested = True
            logger.error("hang watchdog: requested a preemption-style "
                         "emergency checkpoint at the next step boundary")

    # ------------------------------------------------------------------
    # per-step verdict + escalation
    # ------------------------------------------------------------------

    def after_step(self, engine, metrics, overflow):
        """Read the probe's verdict for the step that just ran and apply
        the escalation policy. Returns one of "ok", "warned",
        "quarantined", "rollback"; raises TrainingHealthError on abort."""
        if self.device_probe:
            health = engine.state.health
            if health is None:
                return "ok"
            flags = int(np.asarray(health.flags))
        else:
            # host-optimizer tiers detect non-finite grads on the host
            # regardless of precision; the same scale-search exemption
            # as grad_anomaly_in_jit applies (a dynamic scaler with room
            # to halve owns overflow recovery)
            bad_grad = bool(overflow)
            if bad_grad and engine.dynamic_loss_scale():
                args = engine._config.dynamic_loss_scale_args or {}
                bad_grad = float(engine.state.scale.cur_scale) <= \
                    float(args.get("min_loss_scale", 1))
            self._host_health, _ = probe_update(
                self._host_health, metrics.loss, metrics.grad_norm,
                bad_grad, self.probe_config)
            flags = int(np.asarray(self._host_health.flags))

        self.last_flags = flags
        if flags == 0:
            self.consecutive = 0
            return "ok"

        self.anomalies += 1
        self.consecutive += 1
        record = self._provenance(engine, flags)
        quarantined = self.probe_config.quarantine
        if quarantined:
            self.quarantined += 1
            self.quarantined_windows.append(record)
            del self.quarantined_windows[:-self.max_quarantine_records]
        self._warn(record, quarantined)
        self._record_monitor(engine)
        self._telemetry_anomaly(engine, "+".join(record["kinds"]))

        if self.policy_rank >= POLICIES.index("rollback") and \
                self.consecutive >= self.rollback_after and \
                self._can_rollback(engine):
            if self.rollbacks >= self.max_rollbacks:
                raise TrainingHealthError(
                    f"training health: {self.consecutive} consecutive "
                    f"anomalous steps and the rollback budget "
                    f"({self.max_rollbacks}) is exhausted; aborting. "
                    f"Last anomaly: {record}")
            self._do_rollback(engine, record)
            return "rollback"
        if self.policy_rank >= POLICIES.index("abort") and \
                self.consecutive >= self.abort_after:
            raise TrainingHealthError(
                f"training health: {self.consecutive} consecutive "
                f"anomalous steps (abort_after={self.abort_after}); "
                f"aborting. Last anomaly: {record}")
        return "quarantined" if quarantined else "warned"

    def after_window(self, engine):
        """`train_steps` windows advance many steps in one jitted call;
        per-step escalation is impossible, but the in-jit quarantine
        still protected the weights. Sync the host mirrors and warn."""
        if not self.device_probe or engine.state.health is None:
            return
        health = engine.state.health
        anomalies = int(np.asarray(health.anomalies))
        quarantined = int(np.asarray(health.quarantined))
        if anomalies > self.anomalies:
            logger.warning(
                f"training health: {anomalies - self.anomalies} anomalous "
                f"step(s) inside the fused train_steps window "
                f"({quarantined - self.quarantined} quarantined in-jit); "
                "per-step escalation needs the train_batch loop")
            self._record_monitor(engine)
        self.anomalies = anomalies
        self.quarantined = quarantined

    # ------------------------------------------------------------------
    # actions
    # ------------------------------------------------------------------

    def _provenance(self, engine, flags):
        """Where in the data stream the anomaly happened (PR 3's
        dataloader state_dict provenance: epoch + batch offset)."""
        record = {"step": int(engine.global_steps),
                  "flags": flags,
                  "kinds": decode_flags(flags)}
        loader = getattr(engine, "training_dataloader", None)
        if loader is not None and hasattr(loader, "position"):
            record.update(loader.position())
        return record

    def _warn(self, record, quarantined):
        self._warned += 1
        # rate-limited: first 5, then every 50th — a pathological run
        # must not drown the log in per-step anomaly lines
        if self._warned <= 5 or self._warned % 50 == 0:
            action = "update quarantined" if quarantined else \
                "detection only (policy=warn)"
            log_dist(f"TRAINING HEALTH: anomalous step "
                     f"{record['kinds']} at {record} — {action}; "
                     f"{self.consecutive} consecutive", ranks=[0])

    def _telemetry_anomaly(self, engine, kind):
        """Hand the anomaly to the telemetry layer (runtime/telemetry):
        with `capture_on_anomaly` it snapshots device memory now and
        arms a profiler trace over the next step(s) — once per
        consecutive-anomaly episode."""
        telemetry = getattr(engine, "telemetry", None)
        if telemetry is not None:
            telemetry.on_anomaly(engine, kind)

    def _record_monitor(self, engine):
        monitor = getattr(engine, "monitor", None)
        if monitor is not None and hasattr(monitor, "record_health"):
            monitor.record_health(engine.global_samples, {
                "anomalies": self.anomalies,
                "quarantined": self.quarantined,
                "rollbacks": self.rollbacks,
                "consecutive": self.consecutive,
                "watchdog_fires": self.watchdog_fires,
            })

    def _can_rollback(self, engine):
        manager = getattr(engine, "checkpoint_manager", None)
        return manager is not None and manager.save_dir is not None

    def _do_rollback(self, engine, record):
        """Restore the last committed checkpoint; keep the dataloader at
        its CURRENT position (already past the bad window) instead of
        rewinding it with the checkpoint — replaying the quarantined
        batch would re-trigger the same anomaly on real data corruption."""
        manager = engine.checkpoint_manager
        from .telemetry import NULL_TELEMETRY
        telemetry = getattr(engine, "telemetry", NULL_TELEMETRY)
        with telemetry.span("rollback_restore"):
            manager.wait()   # newest commit must be durable before load
            path, _ = engine.load_checkpoint(manager.save_dir,
                                             load_dataloader_states=False)
        if path is None:
            raise TrainingHealthError(
                f"training health: rollback requested after {record} but "
                f"no committed checkpoint exists under "
                f"{manager.save_dir}")
        self.rollbacks += 1
        self.consecutive = 0
        log_dist(f"TRAINING HEALTH: rolled back to {path} after "
                 f"anomaly {record}; dataloader continues past the "
                 f"quarantined window (rollback {self.rollbacks}/"
                 f"{self.max_rollbacks})", ranks=[0])
        self._record_monitor(engine)
