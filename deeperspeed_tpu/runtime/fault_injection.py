"""Deterministic fault injection for the training-health sentinel.

The recovery machinery in `runtime/sentinel.py` is only trustworthy if
every path can be driven on demand: this harness injects NaN gradients,
loss spikes, and stalled steps at chosen steps so tests and the
`DS_BENCH_SENTINEL=1` bench row exercise detect -> quarantine ->
rollback -> abort and the hang watchdog end to end.

Gating (zero overhead when off):

- config: ``{"training_health": {"fault_injection": {"faults": [...]}}}``
- env:    ``DS_FAULT_INJECT='{"faults": [...]}'`` (JSON, same schema)

When neither is present the engine holds no injector and compiles the
exact same program as before — no extra arguments, no extra ops. When
active, the train step compiles ONE extra variant taking a tiny
``(mode, factor)`` scalar pair; the per-step plan is pure host
bookkeeping.

Fault schema (all faults validated at parse time)::

    {"kind": "nan_grads" | "loss_spike" | "stall"
             | "peer_death" | "slow_peer" | "barrier_timeout"
             | "dcn_delay" | "slice_kill"
             | "prefill_error" | "decode_error" | "decode_stall"
             | "page_pool_pressure",
     "step": N,          # 0-based optimizer-step serial in this process
     "times": 1,         # fires on steps [step, step+times)
     "factor": 1e3,      # loss_spike: loss multiplier;
                         # page_pool_pressure: fraction of the FREE
                         # page pool seized for the step (0 < f <= 1,
                         # default 0.9)
     "seconds": 1.0,     # stall/decode_stall: sleep length;
                         # slow_peer: heartbeat gap;
                         # dcn_delay: injected latency PER EXPOSED
                         # cross-slice crossing (the engine multiplies
                         # by the schedule-aware crossing count —
                         # parallel.schedule.dcn_exposed_crossings)
     "peer": "sim0",     # peer_death/slow_peer: simulated peer name
     "slice": "slice1"}  # slice_kill: multislice slice name to kill

``step`` counts train_batch invocations in THIS process (a monotonic
serial, never rewound by rollback) — so a replayed window after a
rollback does not re-trigger a one-shot fault, which is exactly the
"transient corruption" scenario the recovery tests need. For the
serving engine the serial counts `InferenceEngine.step()` calls.

The elastic kinds are HOST faults (no device-step variant): the engine
pops them via `take_host_faults()` right after `plan_next_step()`.
``peer_death`` / ``slow_peer`` act on a SIMULATED peer registered with
the peer-health monitor (`elasticity/heartbeat.py`) — on one host they
reproduce exactly what a dead/wedged remote host looks like to the
observer; ``barrier_timeout`` arms `utils.distributed.barrier` to raise
a typed `BarrierTimeoutError` on its next rendezvous (e.g. the next
checkpoint commit), driving the fail-fast-and-hand-off path.

The MULTISLICE kinds (docs/multislice.md; require the ``multislice``
config block) make the two-slice regime drivable single-host:
``dcn_delay`` injects cross-slice wire latency host-side and
SCHEDULE-AWARE — ``seconds`` is charged once per EXPOSED DCN crossing
of the step (overlapped wire exposes only fill/drain crossings, the
classic wire every micro-batch hop), folded into the same host sleep
the ``stall`` kind uses; ``slice_kill`` stops the heartbeats of every
simulated peer of the named slice (`PeerHealthMonitor.kill_slice`),
driving slice-granular escalation -> `SliceLostError` -> re-partition.

The SERVING kinds are host faults too, consumed by `InferenceEngine`
(the training engine ignores them): ``prefill_error`` /
``decode_error`` raise an `InjectedServingFault` in place of the
compiled prefill/decode call — driving the quarantine → retry → poison
path; ``decode_stall`` sleeps inside the decode phase (drives the
serving hang watchdog); ``page_pool_pressure`` seizes a fraction of
the free page pool for the step (drives eviction under memory
pressure and the admission controller's shedding signal). Together
they make every shed/quarantine/retry/watchdog path single-host
testable (`docs/inference.md`, the ``chaos`` test marker, and the
``DS_BENCH_SERVE_CHAOS=1`` bench row).
"""

import json
import os

import jax.numpy as jnp

from .config_utils import DeepSpeedConfigError

SERVING_FAULT_KINDS = ("prefill_error", "decode_error", "decode_stall",
                       "page_pool_pressure")
MULTISLICE_FAULT_KINDS = ("dcn_delay", "slice_kill")
FAULT_KINDS = ("nan_grads", "loss_spike", "stall",
               "peer_death", "slow_peer", "barrier_timeout") + \
    MULTISLICE_FAULT_KINDS + SERVING_FAULT_KINDS
HOST_FAULT_KINDS = ("peer_death", "slow_peer", "barrier_timeout") + \
    MULTISLICE_FAULT_KINDS + SERVING_FAULT_KINDS
DEFAULT_SIM_PEER = "sim_peer_0"
PAGE_POOL_PRESSURE_DEFAULT_FRACTION = 0.9


class InjectedServingFault(RuntimeError):
    """The exception `prefill_error`/`decode_error` faults raise in
    place of the compiled serving call — a stand-in for a real
    transient step failure (XLA runtime error, device OOM burst), typed
    so tests can tell injected failures from genuine bugs."""

# device-side injection modes (the (mode, factor) scalar pair)
MODE_NONE = 0
MODE_NAN_GRADS = 1
MODE_LOSS_SPIKE = 2

ENV_VAR = "DS_FAULT_INJECT"


def validate_fault_spec(spec, where="training_health.fault_injection"):
    """Validate an injection spec dict -> normalized list of fault dicts.
    Raises DeepSpeedConfigError on any malformed entry (parse-time
    strictness: a typo'd fault plan must fail at startup, not silently
    never fire)."""
    if not isinstance(spec, dict):
        raise DeepSpeedConfigError(
            f"{where} must be an object with a 'faults' list, got "
            f"{type(spec).__name__}")
    unknown = sorted(set(spec) - {"faults"})
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown {where} key(s) {unknown}; valid keys: ['faults']")
    faults = spec.get("faults", [])
    if not isinstance(faults, (list, tuple)):
        raise DeepSpeedConfigError(
            f"{where}.faults must be a list, got "
            f"{type(faults).__name__}")
    known = {"kind", "step", "times", "factor", "seconds", "peer",
             "slice"}
    out = []
    for i, fault in enumerate(faults):
        if not isinstance(fault, dict):
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}] must be an object, got "
                f"{type(fault).__name__}")
        unknown = sorted(set(fault) - known)
        if unknown:
            raise DeepSpeedConfigError(
                f"Unknown {where}.faults[{i}] key(s) {unknown}; valid "
                f"keys: {sorted(known)}")
        kind = fault.get("kind")
        if kind not in FAULT_KINDS:
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].kind must be one of "
                f"{list(FAULT_KINDS)}, got {kind!r}")
        step = fault.get("step")
        if not isinstance(step, int) or isinstance(step, bool) or step < 0:
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].step must be an int >= 0, got "
                f"{step!r}")
        times = fault.get("times", 1)
        if not isinstance(times, int) or isinstance(times, bool) \
                or times < 1:
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].times must be an int >= 1, got "
                f"{times!r}")
        factor = fault.get("factor",
                           PAGE_POOL_PRESSURE_DEFAULT_FRACTION
                           if kind == "page_pool_pressure" else 1e3)
        seconds = fault.get("seconds", 1.0)
        for key, value in (("factor", factor), ("seconds", seconds)):
            if not isinstance(value, (int, float)) or \
                    isinstance(value, bool) or value <= 0:
                raise DeepSpeedConfigError(
                    f"{where}.faults[{i}].{key} must be a number > 0, "
                    f"got {value!r}")
        if kind == "page_pool_pressure" and factor > 1:
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].factor is the fraction of the "
                f"free page pool to seize for a page_pool_pressure "
                f"fault — must be in (0, 1], got {factor!r}")
        peer = fault.get("peer", DEFAULT_SIM_PEER)
        if not isinstance(peer, str) or not peer:
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].peer must be a non-empty string, "
                f"got {peer!r}")
        if "peer" in fault and kind not in ("peer_death", "slow_peer"):
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].peer only applies to "
                f"peer_death/slow_peer faults, not {kind!r}")
        slice_name = fault.get("slice")
        if kind == "slice_kill":
            if not isinstance(slice_name, str) or not slice_name:
                raise DeepSpeedConfigError(
                    f"{where}.faults[{i}].slice is required for a "
                    f"slice_kill fault (the multislice slice name to "
                    f"kill), got {slice_name!r}")
        elif "slice" in fault:
            raise DeepSpeedConfigError(
                f"{where}.faults[{i}].slice only applies to slice_kill "
                f"faults, not {kind!r}")
        out.append({"kind": kind, "step": step, "times": times,
                    "factor": float(factor), "seconds": float(seconds),
                    "peer": peer, "slice": slice_name,
                    "remaining": times})
    return out


class FaultInjector:
    """Per-process deterministic fault plan.

    `plan_next_step()` is called exactly once per optimizer-step attempt
    and returns ``(mode, factor, stall_seconds)`` for that step; `mode`
    and `factor` ride into the jitted step as scalars (see
    `apply_fault`), `stall_seconds` is slept on the host."""

    def __init__(self, faults):
        self.faults = faults
        self.serial = 0       # monotonic step-attempt counter
        self.fired = []       # (serial, kind) audit trail
        self._pending_host = []   # host faults fired by the last plan

    @classmethod
    def from_config_env(cls, config_spec=None, env=None):
        """Build from the config block and/or the DS_FAULT_INJECT env var
        (faults from both are merged); None when neither is present."""
        env = os.environ if env is None else env
        faults = []
        if config_spec:
            faults += validate_fault_spec(config_spec)
        raw = env.get(ENV_VAR)
        if raw:
            try:
                spec = json.loads(raw)
            except json.JSONDecodeError as e:
                raise DeepSpeedConfigError(
                    f"{ENV_VAR} is not valid JSON: {e}") from e
            faults += validate_fault_spec(spec, where=ENV_VAR)
        if not faults:
            return None
        return cls(faults)

    @property
    def has_device_faults(self):
        return any(f["kind"] in ("nan_grads", "loss_spike")
                   for f in self.faults)

    @property
    def has_serving_faults(self):
        return any(f["kind"] in SERVING_FAULT_KINDS for f in self.faults)

    @property
    def has_multislice_faults(self):
        return any(f["kind"] in MULTISLICE_FAULT_KINDS
                   for f in self.faults)

    @property
    def simulated_peers(self):
        """Names of simulated peers the fault plan will act on — the
        engine registers these with the peer-health monitor up front so
        they heartbeat healthily until their fault fires."""
        return sorted({f["peer"] for f in self.faults
                       if f["kind"] in ("peer_death", "slow_peer")})

    def plan_next_step(self):
        serial = self.serial
        self.serial += 1
        mode, factor, stall = MODE_NONE, 1.0, 0.0
        for fault in self.faults:
            if fault["remaining"] <= 0:
                continue
            if not (fault["step"] <= serial
                    < fault["step"] + fault["times"]):
                continue
            fault["remaining"] -= 1
            self.fired.append((serial, fault["kind"]))
            if fault["kind"] == "nan_grads":
                mode = MODE_NAN_GRADS
            elif fault["kind"] == "loss_spike":
                mode = MODE_LOSS_SPIKE
                factor = fault["factor"]
            elif fault["kind"] == "stall":
                stall = max(stall, fault["seconds"])
            elif fault["kind"] in HOST_FAULT_KINDS:
                self._pending_host.append(dict(fault))
        return mode, factor, stall

    def take_host_faults(self):
        """Host-side faults fired by the most recent `plan_next_step`
        (peer_death / slow_peer / barrier_timeout); the engine applies
        them before dispatching the step. Drains the queue."""
        out, self._pending_host = self._pending_host, []
        return out


def apply_fault(loss, grads, fault):
    """In-jit injection: corrupt the accumulated grads / the step loss
    according to the ``(mode, factor)`` scalar pair. A `mode` of 0 is the
    identity (the `where`s select the clean values)."""
    import jax

    mode, factor = fault
    is_nan = mode == MODE_NAN_GRADS
    grads = jax.tree_util.tree_map(
        lambda g: jnp.where(is_nan, jnp.full(g.shape, jnp.nan, g.dtype), g),
        grads)
    loss = jnp.where(mode == MODE_LOSS_SPIKE,
                     loss * jnp.asarray(factor, loss.dtype), loss)
    return loss, grads
