"""ZeRO Stage 3 — parameter + gradient + optimizer-state sharding
(reference: `deepspeed/runtime/zero/stage3.py:581`).

The reference keeps parameters partitioned at rest (`ds_tensor` shards),
all-gathers each submodule's params just before its forward/backward via
hooks (`fetch_sub_module`/`release_sub_module`, `stage3.py:390/448`),
prefetches along a recorded trace (`PrefetchCoordinator`, `:162`), bounds
live params (`max_live_parameters`), and tiers params/optimizer state to
CPU/NVMe.

TPU mapping, all inside one compiled step:

- params-at-rest sharding   → compute params carry a data-axis
  NamedSharding (see `ZeroShardingRules.param_spec`);
- fetch/release hooks       → XLA materializes each layer's all-gather
  right before its first use and frees the gathered buffer after its last
  use — the compiler performs the reference's hook schedule exactly,
  including overlap (prefetch) via latency-hiding scheduling;
- param_persistence_threshold → small params keep a replicated spec
  (`partition_parameters.py` here), the same keep-persisted trade-off;
- max_live_parameters / prefetch_bucket_size / max_reuse_distance →
  scheduling *hints* in the reference; XLA's scheduler owns these
  decisions. The knobs are accepted (config parity) and the remat policy
  (`runtime/activation_checkpointing`) is the lever that actually trades
  live memory for recompute on TPU;
- CPU/NVMe offload          → `runtime/swap_tensor/*` + the host-Adam tier.

`GatheredParameters` / `zero.Init` live in `partition_parameters.py`.
"""

import numpy as np

import jax
import jax.numpy as jnp

from .stage1 import StepInfo, ZeroOptimizerState
from .stage2 import FP16_DeepSpeedZeroOptimizer_Stage2


def consolidate_params(params, dtype=None):
    """Gather (possibly sharded) params into full host arrays, optionally
    cast — the one consolidation path shared by the standalone stage-3
    optimizer and `engine._zero3_consolidated_fp16_state_dict`."""
    def pull(p):
        if dtype is not None:
            p = p.astype(dtype)
        return np.asarray(jax.device_get(p))

    return jax.tree_util.tree_map(pull, params)

__all__ = ["FP16_DeepSpeedZeroOptimizer_Stage3", "ZeroOptimizerState",
           "StepInfo"]


class FP16_DeepSpeedZeroOptimizer_Stage3(FP16_DeepSpeedZeroOptimizer_Stage2):
    """Full parameter sharding: compute params are data-axis sharded at
    rest (stage=3 switches `param_spec` to sharded), so `init_state` places
    every tensor of the training state as a 1/dp_world shard per device."""

    stage = 3

    def __init__(self, *args, max_live_parameters=1_000_000_000,
                 max_reuse_distance=1_000_000_000,
                 prefetch_bucket_size=50_000_000,
                 param_persistence_threshold=100_000, **kwargs):
        # The three scheduler knobs are accepted for config parity; XLA's
        # latency-hiding scheduler owns the actual fetch/release schedule.
        self.max_live_parameters = max_live_parameters
        self.max_reuse_distance = max_reuse_distance
        self.prefetch_bucket_size = prefetch_bucket_size
        super().__init__(
            *args, param_persistence_threshold=param_persistence_threshold,
            **kwargs)

    def consolidated_fp16_state_dict(self, state, dtype=None):
        """Gather the sharded compute params into full host arrays
        (reference `engine._zero3_consolidated_fp16_state_dict`,
        `engine.py:1820-1915`): every leaf is device_get — which
        all-gathers its shards — and returned as one {path: array} dict.
        `dtype` optionally casts (the engine passes its compute dtype)."""
        return consolidate_params(state.params, dtype=dtype)

    def estimate_state_bytes(self, params):
        """Per-device bytes for params/master/moments under stage 3 — the
        planning number the reference prints via
        `estimate_zero3_model_states_mem_needs` (stage3 utils)."""
        total = sum(int(np.prod(l.shape)) * 1
                    for l in jax.tree_util.tree_leaves(params))
        itemsize = jnp.dtype(self.precision).itemsize
        world = max(self.dp_world, 1)
        # compute shard + fp32 master shard + two fp32 moments shards
        return total * (itemsize + 4 + 8) // world
