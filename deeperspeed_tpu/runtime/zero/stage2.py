"""ZeRO Stage 2 — gradient + optimizer-state sharding
(reference: `deepspeed/runtime/zero/stage2.py:68`).

The reference adds gradient partitioning to stage 1 with backward hooks
that bucket gradients ("IPG" buckets, `stage2.py:563-653`), reduce each
bucket to its owner rank as backward produces it (`reduce_ipg_grads`,
`:953`), optionally on an overlap stream, and optionally offloading grads +
optimizer state to pinned CPU memory stepped by the AVX CPU-Adam.

On TPU every one of those mechanisms is a sharding decision inside one
compiled step:

- bucketing/overlap     → XLA's latency-hiding scheduler fuses and overlaps
  the grad reduce-scatter with remaining backward compute automatically;
- per-rank ownership    → `with_sharding_constraint(grads, data-sharded)`
  lowers the batch-grad mean into `reduce-scatter` (not all-reduce);
- cpu_offload           → the engine's host tier (`runtime/engine.py:600`,
  `ops/adam/cpu_adam_native.py`) steps host-resident masters with the
  native C++ Adam, mirroring `DeepSpeedCPUAdam`.

The class below is stage 1 with the grad constraint enabled (`stage=2`
makes `step()` constrain grads before the update); everything else —
sub-partition math, elastic checkpointing, loss-scale machinery — is
shared with stage 1.
"""

from .stage1 import (FP16_DeepSpeedZeroOptimizer_Stage1, StepInfo,
                     ZeroOptimizerState, flat_sub_partitions,
                     get_group_alignment_padding, sub_partition_bounds,
                     sub_partition_sizes)

__all__ = [
    "FP16_DeepSpeedZeroOptimizer",
    "FP16_DeepSpeedZeroOptimizer_Stage2",
    "ZeroOptimizerState",
    "StepInfo",
    "flat_sub_partitions",
    "get_group_alignment_padding",
    "sub_partition_bounds",
    "sub_partition_sizes",
]


class FP16_DeepSpeedZeroOptimizer_Stage2(FP16_DeepSpeedZeroOptimizer_Stage1):
    """Gradient sharding on top of stage 1: `step()` constrains the grad
    pytree to the data-axis sharding, so XLA reduce-scatters gradients to
    their owning shard instead of all-reducing the full tensors — the
    compiled form of `reduce_ipg_grads` + `average_tensor`
    (`stage2.py:679-1006`)."""

    stage = 2


# The reference names its stage-2 class plain `FP16_DeepSpeedZeroOptimizer`.
FP16_DeepSpeedZeroOptimizer = FP16_DeepSpeedZeroOptimizer_Stage2
