"""ZeRO partitioning as GSPMD sharding specs.

The reference implements ZeRO with hand-rolled machinery: construction-time
parameter scattering via monkeypatched `nn.Module.__init__`
(`zero/partition_parameters.py:265`), backward-hook gradient bucketing
(`zero/stage2.py:563`), and per-submodule gather/release hooks
(`zero/stage3.py:390-531`). On TPU all of that collapses into *where each
array lives on the mesh*:

- stage >= 1: fp32 master params + optimizer moments sharded over ``data``.
- stage >= 2: gradients constrained to the same sharding — XLA lowers the
  batch-mean + constraint into a reduce-scatter instead of an all-reduce.
- stage == 3: the compute (bf16/fp16) params are *also* sharded at rest;
  XLA all-gathers each layer's weights just before use and frees them
  after, which is exactly fetch_sub_module/release_sub_module
  (`stage3.py:390/448`) done by the compiler.

`param_persistence_threshold` maps directly: params smaller than the
threshold stay replicated (the reference keeps them persisted to avoid
latency-bound gathers — same trade-off).
"""

import contextlib

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...parallel.mesh import DATA_AXIS
from ..config_utils import DeepSpeedConfigError


def _shardable_dim(shape, world, threshold_numel=0):
    """Pick the dimension to shard over the data axis: the largest dim
    that divides evenly by `world`; None (replicate) for scalars, params
    under the persistence threshold, or shapes with no evenly-divisible
    dim. Large ragged params (rare: vocabs are conventionally padded to
    the dp world, e.g. 50304) currently forfeit sharding — a
    pad-the-master scheme could lift that."""
    numel = int(np.prod(shape)) if shape else 1
    if not shape or numel < max(threshold_numel, world):
        return None
    divisible = [d for d in range(len(shape)) if shape[d] % world == 0]
    if divisible:
        return max(divisible, key=lambda d: shape[d])
    # No dim divides the dp world (e.g. a 10-class head over 8 ranks):
    # replicate. `device_put` with a NamedSharding requires even shards —
    # GSPMD's padding only applies to in-program sharding constraints.
    return None


class ZeroShardingRules:
    """Derives PartitionSpecs for params/grads/optimizer state per stage."""

    def __init__(self, stage, mesh, param_persistence_threshold=100_000,
                 data_axis=DATA_AXIS):
        if not 0 <= stage <= 3:
            raise DeepSpeedConfigError(f"invalid ZeRO stage {stage}")
        self.stage = stage
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.param_persistence_threshold = param_persistence_threshold

    @property
    def dp_world(self):
        if self.data_axis is None:
            return 1
        return self.mesh.shape[self.data_axis]

    def _spec(self, shape, threshold=0, base=None):
        """Add data-axis sharding to `base` (e.g. a model's tensor-parallel
        spec) on a dim the base leaves unsharded."""
        base_spec = list(base) + [None] * (len(shape) - len(base)) \
            if base is not None else [None] * len(shape)
        if self.data_axis is None or self.dp_world == 1:
            return PartitionSpec(*base_spec)
        free_dims = [d for d in range(len(shape)) if base_spec[d] is None]
        candidate_shape = tuple(shape[d] for d in free_dims)
        dim = _shardable_dim(candidate_shape, self.dp_world, threshold)
        if dim is None:
            return PartitionSpec(*base_spec)
        base_spec[free_dims[dim]] = self.data_axis
        return PartitionSpec(*base_spec)

    # -- per-array spec selection -----------------------------------------

    def param_spec(self, shape, base=None):
        """Compute-dtype params: sharded at rest only at stage 3 (tensor-
        parallel base specs always apply)."""
        if self.stage >= 3:
            return self._spec(shape, self.param_persistence_threshold,
                              base=base)
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    def master_spec(self, shape, base=None):
        """fp32 master params + optimizer moments: sharded from stage 1."""
        if self.stage >= 1:
            return self._spec(shape, base=base)
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    def grad_spec(self, shape, base=None):
        """Gradients: reduce-scattered from stage 2."""
        if self.stage >= 2:
            return self._spec(shape, base=base)
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    # -- pytree helpers ----------------------------------------------------

    def _tree_shardings(self, params, spec_fn):
        return jax.tree_util.tree_map(
            lambda p: NamedSharding(self.mesh, spec_fn(p.shape)), params)

    def param_shardings(self, params):
        return self._tree_shardings(params, self.param_spec)

    def master_shardings(self, params):
        return self._tree_shardings(params, self.master_spec)

    def grad_shardings(self, params):
        return self._tree_shardings(params, self.grad_spec)

    def constrain_grads(self, grads):
        """Apply grad sharding constraints inside a jitted step (this is
        what turns the DP all-reduce into ZeRO-2's reduce-scatter)."""
        if self.stage < 2 or self.data_axis is None:
            return grads
        return jax.tree_util.tree_map(
            lambda g: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, self.grad_spec(g.shape))), grads)

    def place(self, params, spec_fn=None):
        """device_put a pytree with per-leaf ZeRO shardings."""
        spec_fn = spec_fn or self.param_spec
        return jax.tree_util.tree_map(
            lambda p: jax.device_put(
                p, NamedSharding(self.mesh, spec_fn(p.shape))), params)


# ---------------------------------------------------------------------------
# zero.Init / GatheredParameters API compat
# ---------------------------------------------------------------------------

_CURRENT_INIT = None


class Init:
    """Context manager for constructing huge models directly sharded
    (reference `zero/partition_parameters.py:265`).

    The reference monkeypatches tensor construction so each parameter is
    scattered the moment it is created. The JAX-native equivalent: run the
    initializer under `jax.jit` with sharded `out_shardings`, so every
    device materializes only its shard and the full model never exists in
    one HBM. Usage:

        with zero.Init(mesh=mesh, config=ds_config):
            params = zero.Init.materialize(init_fn, rng)
    """

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config=None, enabled=True, mesh=None,
                 stage=3, param_persistence_threshold=100_000):
        if mesh is None:
            mesh = Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
        if config is not None and hasattr(config, "zero_config"):
            stage = config.zero_config.stage
            param_persistence_threshold = \
                config.zero_config.param_persistence_threshold
        self.enabled = enabled
        self.rules = ZeroShardingRules(
            stage=stage if enabled else 0, mesh=mesh,
            param_persistence_threshold=param_persistence_threshold)

    def __enter__(self):
        global _CURRENT_INIT
        self._prev = _CURRENT_INIT
        _CURRENT_INIT = self
        return self

    def __exit__(self, *exc):
        global _CURRENT_INIT
        _CURRENT_INIT = self._prev
        return False

    def materialize(self, init_fn, *args):
        """Run `init_fn(*args) -> params` jitted with sharded outputs."""
        shapes = jax.eval_shape(init_fn, *args)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.rules.mesh,
                                    self.rules.param_spec(s.shape)), shapes)
        return jax.jit(init_fn, out_shardings=shardings)(*args)


def current_init_context():
    return _CURRENT_INIT


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank=None, fwd_module=None,
                       enabled=True):
    """Yield fully-replicated host-side views of (possibly sharded) params
    (reference `partition_parameters.py:1002`). Mutations inside the
    context are NOT written back automatically (JAX arrays are immutable);
    use the yielded list's `.result()`-style replacement instead."""
    if not enabled:
        yield params
        return
    gathered = jax.tree_util.tree_map(lambda p: np.asarray(jax.device_get(p)),
                                      params)
    yield gathered


# External-parameter registry (reference `partition_parameters.py:56`): in
# the reference, cross-module parameter access defeats the hook-based
# gather so users must register such params. With compiler-managed
# gathering there is nothing to defeat; the registry is a no-op kept for
# API compatibility.
_EXTERNAL_PARAMS = {}


def register_external_parameter(module, parameter):
    _EXTERNAL_PARAMS.setdefault(id(module), []).append(parameter)


def unregister_external_parameter(module, parameter):
    params = _EXTERNAL_PARAMS.get(id(module), [])
    if parameter in params:
        params.remove(parameter)
