"""ZeRO partitioning as GSPMD sharding specs.

The reference implements ZeRO with hand-rolled machinery: construction-time
parameter scattering via monkeypatched `nn.Module.__init__`
(`zero/partition_parameters.py:265`), backward-hook gradient bucketing
(`zero/stage2.py:563`), and per-submodule gather/release hooks
(`zero/stage3.py:390-531`). On TPU all of that collapses into *where each
array lives on the mesh*:

- stage >= 1: fp32 master params + optimizer moments sharded over ``data``.
- stage >= 2: gradients constrained to the same sharding — XLA lowers the
  batch-mean + constraint into a reduce-scatter instead of an all-reduce.
- stage == 3: the compute (bf16/fp16) params are *also* sharded at rest;
  XLA all-gathers each layer's weights just before use and frees them
  after, which is exactly fetch_sub_module/release_sub_module
  (`stage3.py:390/448`) done by the compiler.

`param_persistence_threshold` maps directly: params smaller than the
threshold stay replicated (the reference keeps them persisted to avoid
latency-bound gathers — same trade-off).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...parallel.mesh import DATA_AXIS
from ..config_utils import DeepSpeedConfigError


def _shardable_dim(shape, world, threshold_numel=0):
    """Pick the dimension to shard over the data axis: the largest dim
    that divides evenly by `world`; None for scalars, params under the
    persistence threshold, or shapes with no evenly-divisible dim. Ragged
    shapes do NOT forfeit sharding: callers route them through
    `master_pad_info` (pad-the-master, the reference's flatten-and-
    partition `stage2.py:196-374` done as a padded flat shard)."""
    numel = int(np.prod(shape)) if shape else 1
    if not shape or numel < max(threshold_numel, world):
        return None
    divisible = [d for d in range(len(shape)) if shape[d] % world == 0]
    if divisible:
        return max(divisible, key=lambda d: shape[d])
    # No dim divides the dp world (e.g. an unpadded 50257 vocab over 8
    # ranks): `device_put` with a NamedSharding requires even shards, so
    # dim-sharding is out — the flat-pad layout below handles these.
    return None


class FlatPad:
    """Descriptor for a leaf stored flat-padded: `shape` is the natural
    (compute) shape, `numel` its true size, `padded` the dp-divisible
    length of the stored 1-D master/moment buffer. Deliberately NOT a
    NamedTuple: it must be an opaque pytree leaf, not a container."""

    __slots__ = ("shape", "numel", "padded")

    def __init__(self, shape, numel, padded):
        self.shape = tuple(shape)
        self.numel = numel
        self.padded = padded

    def __repr__(self):
        return (f"FlatPad(shape={self.shape}, numel={self.numel}, "
                f"padded={self.padded})")


def flat_pad(arr, info):
    """Natural-shaped array → padded flat buffer (zero-padded tail). Works
    on jnp (traced or not) and numpy arrays alike."""
    flat = jnp.ravel(arr)
    return jnp.pad(flat, (0, info.padded - info.numel))


def flat_unpad(flat, info):
    """Padded flat buffer → natural-shaped array."""
    return flat[:info.numel].reshape(info.shape)


def is_layout_shaped(x, info):
    """Is `x` actually stored in `info`'s flat-padded layout? Optimizer
    states can carry fields whose pytree structure mirrors the masters
    but whose leaves are differently shaped (e.g. OnebitLamb's per-param
    () scalars); those must pass through layout conversion untouched."""
    return getattr(x, "ndim", None) == 1 and x.size == info.padded


def is_natural_shaped(x, info):
    """Does `x` have `info`'s natural (compute) shape? See
    `is_layout_shaped` for why mirroring trees can disagree."""
    return tuple(getattr(x, "shape", ())) == info.shape


def to_natural_leaf(x, info):
    """Layout→natural for one leaf: unpad layout-shaped leaves, pass
    scalars (mirroring opt-state fields) and already-natural leaves, and
    fail LOUDLY on anything else — a silently forwarded wrong-width leaf
    would only surface as an opaque shape error deep in the jitted step
    (or a corrupt re-saved checkpoint)."""
    if not info:
        return x
    if is_layout_shaped(x, info):
        return flat_unpad(x, info)
    if getattr(x, "ndim", 0) == 0 or is_natural_shaped(x, info):
        return x
    raise ValueError(
        f"leaf shape {tuple(x.shape)} matches neither the stored flat-pad "
        f"layout ({info.padded},) nor the natural shape {info.shape} — "
        "checkpoint/model geometry mismatch?")


def to_layout_leaf(x, info):
    """Natural→layout for one leaf (see `to_natural_leaf`)."""
    if not info:
        return x
    if is_natural_shaped(x, info):
        return flat_pad(x, info)
    if getattr(x, "ndim", 0) == 0 or is_layout_shaped(x, info):
        return x
    raise ValueError(
        f"leaf shape {tuple(x.shape)} matches neither the natural shape "
        f"{info.shape} nor the stored flat-pad layout ({info.padded},) — "
        "checkpoint/model geometry mismatch?")


def map_master_fields(opt_state, master_def, fn, *rest, passthrough=None):
    """Rebuild an optimizer-state NamedTuple, applying `fn(field, *extras)`
    to fields whose pytree structure mirrors the master params
    (moments), and `passthrough` (default: identity on the first item) to
    the rest (e.g. the scalar step counter). `rest` are parallel
    opt-state-like containers zipped field-wise into fn/passthrough —
    used to pair a natural-shaped tree with its layout template."""
    fields = []
    for items in zip(opt_state, *rest):
        field = items[0]
        try:
            mirrors = jax.tree_util.tree_structure(field) == master_def
        except Exception:
            mirrors = False
        if mirrors:
            fields.append(fn(*items))
        elif passthrough is not None:
            fields.append(passthrough(*items))
        else:
            fields.append(field)
    return type(opt_state)(*fields)


class ZeroShardingRules:
    """Derives PartitionSpecs for params/grads/optimizer state per stage."""

    def __init__(self, stage, mesh, param_persistence_threshold=100_000,
                 data_axis=DATA_AXIS):
        if not 0 <= stage <= 3:
            raise DeepSpeedConfigError(f"invalid ZeRO stage {stage}")
        self.stage = stage
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.param_persistence_threshold = param_persistence_threshold

    @property
    def dp_world(self):
        if self.data_axis is None:
            return 1
        return self.mesh.shape[self.data_axis]

    def _spec(self, shape, threshold=0, base=None):
        """Add data-axis sharding to `base` (e.g. a model's tensor-parallel
        spec) on a dim the base leaves unsharded."""
        base_spec = list(base) + [None] * (len(shape) - len(base)) \
            if base is not None else [None] * len(shape)
        if self.data_axis is None or self.dp_world == 1:
            return PartitionSpec(*base_spec)
        free_dims = [d for d in range(len(shape)) if base_spec[d] is None]
        candidate_shape = tuple(shape[d] for d in free_dims)
        dim = _shardable_dim(candidate_shape, self.dp_world, threshold)
        if dim is None:
            return PartitionSpec(*base_spec)
        base_spec[free_dims[dim]] = self.data_axis
        return PartitionSpec(*base_spec)

    # -- per-array spec selection -----------------------------------------

    def param_spec(self, shape, base=None):
        """Compute-dtype params: sharded at rest only at stage 3 (tensor-
        parallel base specs always apply)."""
        if self.stage >= 3:
            return self._spec(shape, self.param_persistence_threshold,
                              base=base)
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    def master_spec(self, shape, base=None):
        """fp32 master params + optimizer moments: sharded from stage 1."""
        if self.stage >= 1:
            return self._spec(shape, base=base)
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    def master_pad_info(self, shape, base=None):
        """`FlatPad` descriptor when the leaf's master/moments must be
        stored flat-padded to get sharded at all: stage >= 1, a data axis
        with world > 1, the leaf is at least world-sized, no tensor-
        parallel base sharding, and no natural dim divides the dp world.
        Returns None when normal dim-sharding (or replication of tiny
        leaves) applies. This is the reference's pad-and-flatten
        partitioning (`stage2.py:196-374`, `stage1.py:328-465`): every
        large param gets 1/world of its fp32 state per rank, vocab-50257
        included."""
        if self.stage < 1 or self.data_axis is None or self.dp_world == 1:
            return None
        if base is not None and any(a is not None for a in base):
            return None  # TP-sharded leaves keep their dim layout
        numel = int(np.prod(shape)) if shape else 1
        if not shape or numel < self.dp_world:
            return None
        if self.data_axis in self._spec(shape):
            return None  # a natural dim shards evenly
        world = self.dp_world
        padded = -(-numel // world) * world
        return FlatPad(tuple(shape), numel, padded)

    def flat_master_sharding(self):
        """Sharding of a flat-padded master/moment buffer."""
        return NamedSharding(self.mesh, PartitionSpec(self.data_axis))

    def param_pad_info(self, shape, base=None):
        """`FlatPad` descriptor for a COMPUTE param stored flat-padded at
        rest at stage 3 (ragged leaves that would otherwise replicate —
        the unpad inside the jitted step becomes the stage-3 all-gather).
        Honors `param_persistence_threshold`: small params stay
        replicated in natural shape (reference
        `partition_parameters.py:610-744` persistence semantics)."""
        if self.stage < 3:
            return None
        numel = int(np.prod(shape)) if shape else 1
        if numel < self.param_persistence_threshold:
            return None
        return self.master_pad_info(shape, base=base)

    def grad_spec(self, shape, base=None):
        """Gradients: reduce-scattered from stage 2."""
        if self.stage >= 2:
            return self._spec(shape, base=base)
        return PartitionSpec(*base) if base is not None else PartitionSpec()

    # -- pytree helpers ----------------------------------------------------

    def _tree_shardings(self, params, spec_fn):
        return jax.tree_util.tree_map(
            lambda p: NamedSharding(self.mesh, spec_fn(p.shape)), params)

    def param_shardings(self, params):
        return self._tree_shardings(params, self.param_spec)

    def master_shardings(self, params):
        return self._tree_shardings(params, self.master_spec)

    def grad_shardings(self, params):
        return self._tree_shardings(params, self.grad_spec)

    def constrain_grads(self, grads):
        """Apply grad sharding constraints inside a jitted step (this is
        what turns the DP all-reduce into ZeRO-2's reduce-scatter)."""
        if self.stage < 2 or self.data_axis is None:
            return grads
        return jax.tree_util.tree_map(
            lambda g: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, self.grad_spec(g.shape))), grads)

    def place(self, params, spec_fn=None):
        """device_put a pytree with per-leaf ZeRO shardings."""
        spec_fn = spec_fn or self.param_spec
        return jax.tree_util.tree_map(
            lambda p: jax.device_put(
                p, NamedSharding(self.mesh, spec_fn(p.shape))), params)


# ---------------------------------------------------------------------------
# zero.Init / GatheredParameters API compat
# ---------------------------------------------------------------------------

_CURRENT_INIT = None


class Init:
    """Context manager for constructing huge models directly sharded
    (reference `zero/partition_parameters.py:265`).

    The reference monkeypatches tensor construction so each parameter is
    scattered the moment it is created. The JAX-native equivalent: run the
    initializer under `jax.jit` with sharded `out_shardings`, so every
    device materializes only its shard and the full model never exists in
    one HBM. Usage:

        with zero.Init(mesh=mesh, config=ds_config):
            params = zero.Init.materialize(init_fn, rng)
    """

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config=None, enabled=True, mesh=None,
                 stage=3, param_persistence_threshold=100_000):
        if mesh is None:
            mesh = Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
        if config is not None and hasattr(config, "zero_config"):
            stage = config.zero_config.stage
            param_persistence_threshold = \
                config.zero_config.param_persistence_threshold
        self.enabled = enabled
        self.rules = ZeroShardingRules(
            stage=stage if enabled else 0, mesh=mesh,
            param_persistence_threshold=param_persistence_threshold)

    def __enter__(self):
        global _CURRENT_INIT
        self._prev = _CURRENT_INIT
        _CURRENT_INIT = self
        return self

    def __exit__(self, *exc):
        global _CURRENT_INIT
        _CURRENT_INIT = self._prev
        return False

    def materialize(self, init_fn, *args):
        """Run `init_fn(*args) -> params` jitted with sharded outputs."""
        shapes = jax.eval_shape(init_fn, *args)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.rules.mesh,
                                    self.rules.param_spec(s.shape)), shapes)
        return jax.jit(init_fn, out_shardings=shardings)(*args)


def current_init_context():
    return _CURRENT_INIT


class GatheredParameters:
    """Context manager yielding fully-gathered MUTABLE host views of
    (possibly sharded) params, with write-back on exit (reference
    `partition_parameters.py:1002`).

    Reference semantics: under ``modifier_rank=r``, code inside the
    context mutates the gathered params and on exit rank r's values are
    scattered back to the partitioned storage. Here (single-controller
    SPMD — every process traces the same program) ``modifier_rank`` not
    None simply enables write-back: the yielded numpy arrays are
    re-placed with each param's original sharding/dtype on exit, and the
    result is available as ``.updated`` (JAX arrays are immutable, so the
    caller swaps the tree rather than relying on aliasing)::

        gp = GatheredParameters(params, modifier_rank=0)
        with gp as full:
            full["w"][:2] = 0.0
        params = gp.updated

    With ``modifier_rank=None`` (read-only gather, the reference default)
    mutations are discarded, as in the reference. Engines wire an
    ``on_exit`` callback to fold mutations into live training state — see
    `DeepSpeedEngine.gathered_parameters`.
    """

    def __init__(self, params, modifier_rank=None, fwd_module=None,
                 enabled=True, on_exit=None, select=None):
        self.params = params
        self.modifier_rank = modifier_rank
        self.enabled = enabled
        self.updated = None
        self._on_exit = on_exit
        self._view = None
        # select: per-leaf predicate on the tree path ("blocks/0/mlp/..."),
        # so callers gather a SUB-TREE instead of stalling on a whole-
        # model host materialization (reference gathers are per-param,
        # `partition_parameters.py:1002`). Unselected leaves stay as
        # (immutable) device arrays in the yielded tree.
        self._select = select

    def _selected(self, path):
        if self._select is None:
            return True
        # DictKey → .key, SequenceKey → .idx, GetAttrKey → .name
        key = "/".join(
            str(getattr(p, "key",
                        getattr(p, "idx", getattr(p, "name", p))))
            for p in path)
        return self._select(key)

    def __enter__(self):
        if not self.enabled:
            self._view = self.params
            return self.params
        # np.array (not asarray): a mutable copy, never a read-only view
        self._view = jax.tree_util.tree_map_with_path(
            lambda path, p: np.array(jax.device_get(p))
            if self._selected(path) else p, self.params)
        return self._view

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None or not self.enabled:
            return False
        if self.modifier_rank is not None:
            if self._on_exit is not None:
                # the callback owns the write-back; don't also materialize
                # .updated (a second full-model host→device copy)
                self._on_exit(self._view)
            else:
                self.updated = jax.tree_util.tree_map_with_path(
                    lambda path, v, p: (jax.device_put(
                        jnp.asarray(v, p.dtype),
                        getattr(p, "sharding", None))
                        if hasattr(p, "sharding")
                        else jnp.asarray(v, p.dtype))
                    if self._selected(path) else p,
                    self._view, self.params)
        return False


# External-parameter registry (reference `partition_parameters.py:56`): in
# the reference, cross-module parameter access defeats the hook-based
# gather so users must register such params. With compiler-managed
# gathering there is nothing to defeat; the registry is a no-op kept for
# API compatibility.
_EXTERNAL_PARAMS = {}


def register_external_parameter(module, parameter):
    _EXTERNAL_PARAMS.setdefault(id(module), []).append(parameter)


def unregister_external_parameter(module, parameter):
    params = _EXTERNAL_PARAMS.get(id(module), [])
    if parameter in params:
        params.remove(parameter)
