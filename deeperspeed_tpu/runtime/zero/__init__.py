from .config import DeepSpeedZeroConfig
from .stage1 import FP16_DeepSpeedZeroOptimizer_Stage1
from .stage2 import (FP16_DeepSpeedZeroOptimizer,
                     FP16_DeepSpeedZeroOptimizer_Stage2)
from .stage3 import FP16_DeepSpeedZeroOptimizer_Stage3
from .contiguous_memory_allocator import ContiguousMemoryAllocator
from .partition_parameters import (GatheredParameters, Init,
                                   ZeroShardingRules,
                                   register_external_parameter,
                                   unregister_external_parameter)
from .tiling import TiledLinear, memory_efficient_linear
