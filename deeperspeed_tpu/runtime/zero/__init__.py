from .config import DeepSpeedZeroConfig
from .partition_parameters import (GatheredParameters, Init,
                                   ZeroShardingRules,
                                   register_external_parameter,
                                   unregister_external_parameter)
