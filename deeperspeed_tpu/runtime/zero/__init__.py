from .config import DeepSpeedZeroConfig
from .contiguous_memory_allocator import ContiguousMemoryAllocator
from .partition_parameters import (GatheredParameters, Init,
                                   ZeroShardingRules,
                                   register_external_parameter,
                                   unregister_external_parameter)
from .tiling import TiledLinear, memory_efficient_linear
