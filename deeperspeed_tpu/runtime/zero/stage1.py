"""ZeRO Stage 1 — optimizer-state sharding
(reference: `deepspeed/runtime/zero/stage1.py:100`).

The reference keeps fp32 master *sub-partitions* per data-parallel rank,
reduce-scatters gradients into them, steps locally, and all-gathers updated
fp16 params. On TPU the same ownership structure is expressed as sharding:
fp32 masters and optimizer moments carry a `data`-axis NamedSharding while
gradients and compute params stay replicated, and XLA emits exactly the
reference's reduce-scatter + local-step + all-gather when the update is
jitted. This module packages that as a standalone optimizer class (the
engine wires the same rules internally; see `runtime/engine.py`).

Sub-partition arithmetic (`get_group_alignment_padding`,
`flat_sub_partitions` — reference `stage1.py:328-465`) is kept as pure
functions: checkpoint tooling (`utils/zero_to_fp32.py`) and tests use them
to reason about how a flat buffer maps onto dp ranks.
"""

from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...parallel.mesh import DATA_AXIS
from ..utils import clip_grad_norm_, global_norm
from ..fp16.loss_scaler import (LossScaleState, grads_finite,
                                init_loss_scale_state, update_loss_scale)
from .partition_parameters import (ZeroShardingRules, flat_pad, flat_unpad,
                                   map_master_fields, to_layout_leaf,
                                   to_natural_leaf)


# ---------------------------------------------------------------------------
# flat sub-partition math (reference stage1.py:328-465)
# ---------------------------------------------------------------------------

def sub_partition_sizes(numel, world, sub_partition_count=1):
    """Split `numel` into world*sub_partition_count aligned pieces; the last
    piece absorbs the remainder, as the reference pads the final
    sub-partition (`stage1.py:360`)."""
    parts = world * sub_partition_count
    base = numel // parts
    sizes = [base] * parts
    sizes[-1] += numel - base * parts
    return sizes


def sub_partition_bounds(numel, world, sub_partition_count=1):
    """[(start, end)] for each sub-partition, rank-major order: rank r owns
    pieces [r, r+world, r+2*world, ...] (the reference's round-robin
    comm-interleaved layout, `stage1.py:417-440`)."""
    sizes = sub_partition_sizes(numel, world, sub_partition_count)
    bounds, off = [], 0
    for s in sizes:
        bounds.append((off, off + s))
        off += s
    return bounds


def flat_sub_partitions(flat, world, sub_partition_count=1):
    """Slice a flat array into per-rank lists of sub-partition views."""
    numel = flat.shape[0]
    bounds = sub_partition_bounds(numel, world, sub_partition_count)
    per_rank = [[] for _ in range(world)]
    for i, (lo, hi) in enumerate(bounds):
        per_rank[i % world].append(flat[lo:hi])
    return per_rank


def get_group_alignment_padding(numel, world, alignment=1):
    """Padding needed so `numel` splits evenly into world pieces of
    `alignment`-multiple size (reference `stage1.py:343`)."""
    chunk = world * alignment
    return (chunk - numel % chunk) % chunk


# ---------------------------------------------------------------------------
# standalone stage-1 optimizer
# ---------------------------------------------------------------------------

class ZeroOptimizerState(NamedTuple):
    params: Any               # compute dtype; replicated (stage<3)
    master: Any               # fp32; data-axis sharded
    opt_state: Any            # moments follow master sharding
    scale: LossScaleState


class StepInfo(NamedTuple):
    overflow: jnp.ndarray
    grad_norm: jnp.ndarray
    loss_scale: jnp.ndarray


class FP16_DeepSpeedZeroOptimizer_Stage1:
    """Optimizer-state sharding over the `data` mesh axis.

    `base_optimizer` must expose init_state/update/param_groups (FusedAdam,
    FusedLamb). `precision` mirrors the fork's bf16 support
    (`stage1.py:117-118`): bf16 grads are upcast to fp32 before the
    (implicit) reduce, exactly the fork's fp32-allreduce-for-bf16.
    """

    stage = 1

    def __init__(self, init_optimizer, mesh=None, data_axis=DATA_AXIS,
                 static_loss_scale=1.0, dynamic_loss_scale=False,
                 dynamic_loss_args=None, clip_grad=0.0,
                 precision=jnp.float16, param_persistence_threshold=0,
                 mpu=None, verbose=False):
        self.optimizer = init_optimizer
        self.clip_grad = clip_grad
        self.precision = precision
        self.dynamic = dynamic_loss_scale
        args = dynamic_loss_args or {}
        self._init_scale = (args.get("init_scale", 2 ** 32)
                            if dynamic_loss_scale else static_loss_scale)
        self.scale_window = args.get("scale_window", 1000)
        self.min_scale = args.get("min_scale", 1)
        self.delayed_shift = args.get("delayed_shift", 1)
        if mesh is None:
            mesh = Mesh(np.asarray(jax.devices()), (data_axis,))
        self.mesh = mesh
        self.rules = ZeroShardingRules(
            stage=self.stage, mesh=mesh,
            param_persistence_threshold=param_persistence_threshold,
            data_axis=data_axis)
        self.mpu = mpu

    # -- torch-ish surface -------------------------------------------------

    @property
    def param_groups(self):
        return self.optimizer.param_groups

    @property
    def loss_scale(self):
        """Initial/static scale. The live dynamic scale is training state —
        read it with `get_loss_scale(state)`."""
        return self._init_scale

    def get_loss_scale(self, state):
        """Current loss scale (the reference property reads its scaler's
        mutable cur_scale; here the scale lives in the state pytree)."""
        return float(state.scale.cur_scale)

    @property
    def dp_world(self):
        return self.rules.dp_world

    # -- placement ---------------------------------------------------------

    def init_state(self, params):
        # Ragged leaves (no dp-divisible dim) store master/moments as
        # padded flat 1-D shards — the reference's pad-and-flatten
        # partitioning (`stage1.py:328-465`); see `FlatPad`.
        self._padinfo = jax.tree_util.tree_map(
            lambda p: self.rules.master_pad_info(p.shape) or False, params)
        if hasattr(self.optimizer, "pad_info"):
            # 1-bit optimizers: compression must skip flat-pad tails.
            self.optimizer.pad_info = self._padinfo

        def make_master(p, info):
            m = jnp.asarray(p, jnp.float32)
            if info:
                return jax.device_put(flat_pad(m, info),
                                      self.rules.flat_master_sharding())
            return jax.device_put(
                m, NamedSharding(self.mesh, self.rules.master_spec(p.shape)))

        master = jax.tree_util.tree_map(make_master, params, self._padinfo)
        compute = jax.tree_util.tree_map(
            lambda p: jax.device_put(
                jnp.asarray(p, self.precision),
                NamedSharding(self.mesh, self.rules.param_spec(p.shape))),
            params)
        opt_state = self.optimizer.init_state(master)
        opt_state = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, NamedSharding(
                    self.mesh,
                    self.rules.master_spec(x.shape)
                    if getattr(x, "ndim", 0) > 0 else PartitionSpec())),
            opt_state)
        scale = init_loss_scale_state(init_scale=self._init_scale,
                                      delayed_shift=self.delayed_shift,
                                      static=not self.dynamic)
        return ZeroOptimizerState(params=compute, master=master,
                                  opt_state=opt_state, scale=scale)

    def scale_loss(self, loss, state):
        return loss * state.scale.cur_scale.astype(loss.dtype)

    # -- jit-safe step -----------------------------------------------------

    def step(self, state, grads, lr=None):
        """grads = d(scaled loss)/d(params). Unscale → clip → sharded
        update → recast; the master sharding makes XLA reduce-scatter the
        grads to their owners and all-gather the updated params — the
        reference's explicit schedule (`stage1.py:629-784`)."""
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) / state.scale.cur_scale, grads)

        finite = grads_finite(grads)
        overflow = jnp.logical_not(finite)
        grad_norm = global_norm(grads)
        if self.clip_grad > 0:
            grads, _ = clip_grad_norm_(grads, self.clip_grad, norm=grad_norm)

        if self.stage >= 2:
            grads = self.rules.constrain_grads(grads)

        # Move ragged-leaf grads into the flat-padded master layout.
        grads = jax.tree_util.tree_map(
            lambda g, info: jax.lax.with_sharding_constraint(
                flat_pad(g, info), self.rules.flat_master_sharding())
            if info else g,
            grads, self._padinfo)

        new_master, new_opt = self.optimizer.update(
            grads, state.opt_state, state.master, lr=lr)

        new_master = jax.tree_util.tree_map(
            lambda n, o: jnp.where(overflow, o, n), new_master, state.master)
        new_opt = jax.tree_util.tree_map(
            lambda n, o: jnp.where(overflow, o, n), new_opt, state.opt_state)
        new_params = jax.tree_util.tree_map(
            lambda p, m, info: jax.lax.with_sharding_constraint(
                (flat_unpad(m, info) if info else m).astype(p.dtype),
                NamedSharding(self.mesh, self.rules.param_spec(p.shape))),
            state.params, new_master, self._padinfo)

        if self.dynamic:
            new_scale = update_loss_scale(
                state.scale, overflow, scale_window=self.scale_window,
                min_scale=self.min_scale, delayed_shift=self.delayed_shift)
        else:
            new_scale = state.scale._replace(
                cur_iter=state.scale.cur_iter + 1)

        return (ZeroOptimizerState(params=new_params, master=new_master,
                                   opt_state=new_opt, scale=new_scale),
                StepInfo(overflow=overflow, grad_norm=grad_norm,
                         loss_scale=state.scale.cur_scale))

    # -- checkpoint surface (elastic; reference stage1 state-dict machinery)

    def _opt_to_natural(self, opt_state):
        master_def = jax.tree_util.tree_structure(self._padinfo)
        return map_master_fields(
            opt_state, master_def, lambda t: jax.tree_util.tree_map(
                lambda x, i: np.asarray(to_natural_leaf(x, i)),
                t, self._padinfo))

    def _opt_to_layout(self, opt_state, like):
        master_def = jax.tree_util.tree_structure(self._padinfo)

        def relayout(t, cur):
            return jax.tree_util.tree_map(
                lambda x, i, c: jax.device_put(
                    to_layout_leaf(jnp.asarray(x, jnp.float32)
                                   if i else jnp.asarray(x), i), c.sharding),
                t, self._padinfo, cur)

        return map_master_fields(opt_state, master_def, relayout, like,
                                 passthrough=lambda n, c: jnp.asarray(n))

    def state_dict(self, state):
        """Per-dp-rank flat sub-partitions of master+moments, so a restart
        at a different world size can merge + re-slice (the checkpoint
        layer does the same for the engine path)."""
        # Unpad flat-padded leaves first: the padded length depends on the
        # dp world, and this state_dict must merge across world sizes.
        info_leaves = jax.tree_util.tree_leaves(self._padinfo)
        flat_master = jnp.concatenate(
            [jnp.ravel(flat_unpad(l, i) if i else l)
             for l, i in zip(jax.tree_util.tree_leaves(state.master),
                             info_leaves)])
        sub_parts = flat_sub_partitions(np.asarray(flat_master),
                                        self.dp_world)
        return {
            "zero_stage": self.stage,
            "partition_count": self.dp_world,
            "cur_scale": float(state.scale.cur_scale),
            "cur_iter": int(state.scale.cur_iter),
            "local_sub_partitions_of_fp32_groups":
                [[np.asarray(p) for p in parts] for parts in sub_parts],
            "optimizer_state_dict": self.optimizer.state_dict(
                self._opt_to_natural(state.opt_state)),
        }

    def load_state_dict(self, state, sd, load_optimizer_states=True):
        parts = sd["local_sub_partitions_of_fp32_groups"]
        world = sd["partition_count"]
        # rank-major round robin → flat order (elastic merge).
        n_pieces = sum(len(p) for p in parts)
        ordered = [None] * n_pieces
        for rank, plist in enumerate(parts):
            for j, piece in enumerate(plist):
                ordered[rank + j * world] = piece
        flat = np.concatenate([np.asarray(p).ravel() for p in ordered])

        leaves = jax.tree_util.tree_leaves(state.master)
        info_leaves = jax.tree_util.tree_leaves(self._padinfo)
        new_leaves, off = [], 0
        for leaf, info in zip(leaves, info_leaves):
            n = info.numel if info else (
                int(np.prod(leaf.shape)) if leaf.shape else 1)
            piece = jnp.asarray(flat[off:off + n], jnp.float32)
            piece = flat_pad(piece, info) if info else piece.reshape(
                leaf.shape)
            new_leaves.append(jax.device_put(piece, leaf.sharding))
            off += n
        master = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(state.master), new_leaves)
        params = jax.tree_util.tree_map(
            lambda p, m, info: jax.device_put(
                (flat_unpad(m, info) if info else m).astype(p.dtype),
                p.sharding),
            state.params, master, self._padinfo)
        opt_state = state.opt_state
        if load_optimizer_states and "optimizer_state_dict" in sd:
            opt_state = self._opt_to_layout(
                self.optimizer.load_state_dict(sd["optimizer_state_dict"]),
                state.opt_state)
        scale = state.scale._replace(
            cur_scale=jnp.asarray(sd["cur_scale"], jnp.float32),
            cur_iter=jnp.asarray(sd["cur_iter"], jnp.int32))
        return ZeroOptimizerState(params=params, master=master,
                                  opt_state=opt_state, scale=scale)
