"""ZeRO + offload config-schema keys (reference: `deepspeed/runtime/zero/
constants.py`, `offload_constants.py`).

On TPU the stages map to GSPMD sharding over the `data` mesh axis:
stage 1 shards optimizer state, stage 2 additionally reduce-scatters
gradients, stage 3 additionally shards parameters at rest. The bucket /
overlap / prefetch knobs are accepted for config compatibility and act as
XLA tuning hints (or no-ops) rather than hand-rolled bucketing.
"""

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = False

ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = False
ZERO3_OPTIMIZATION_OVERLAP_COMM_DEFAULT = True

ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = False

ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500_000_000

ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500_000_000
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED = "allgather_size"

ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True

ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

# Deprecated spellings kept for config compat; folded into offload_optimizer.
ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False
ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS = "cpu_offload_params"
ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT = False
ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY = "cpu_offload_use_pin_memory"
ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT = False

ZERO_OPTIMIZATION_SUB_GROUP_SIZE = "sub_group_size"
ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT = 1_000_000_000_000

ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT = 1_000_000_000

ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT = 1_000_000_000

ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT = 50_000_000

# explicit-dataflow collective schedule sub-block (parallel/schedule.py):
# {"mode": "gspmd"|"explicit", "prefetch_depth", "bucket_mb",
#  "group_layers"} — parsed at checkpoint-block strictness
ZERO_OPTIMIZATION_SCHEDULE = "schedule"

ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT = 100_000

ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE = (
    "stage3_gather_fp16_weights_on_model_save")
ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT = False

# ---------------------------------------------------------------------------
# Offload sub-blocks ("offload_param" / "offload_optimizer")
# ---------------------------------------------------------------------------
OFFLOAD_CPU_DEVICE = "cpu"
OFFLOAD_NVME_DEVICE = "nvme"

OFFLOAD_PARAM = "offload_param"
OFFLOAD_PARAM_DEVICE = "device"
OFFLOAD_PARAM_NVME_PATH = "nvme_path"
OFFLOAD_PARAM_BUFFER_COUNT = "buffer_count"
OFFLOAD_PARAM_BUFFER_SIZE = "buffer_size"
OFFLOAD_PARAM_MAX_IN_CPU = "max_in_cpu"
OFFLOAD_PARAM_PIN_MEMORY = "pin_memory"

OFFLOAD_OPTIMIZER = "offload_optimizer"
OFFLOAD_OPTIMIZER_DEVICE = "device"
OFFLOAD_OPTIMIZER_NVME_PATH = "nvme_path"
OFFLOAD_OPTIMIZER_BUFFER_COUNT = "buffer_count"
OFFLOAD_OPTIMIZER_PIN_MEMORY = "pin_memory"
OFFLOAD_OPTIMIZER_PIPELINE_READ = "pipeline_read"
OFFLOAD_OPTIMIZER_PIPELINE_WRITE = "pipeline_write"
OFFLOAD_OPTIMIZER_PIPELINE = "pipeline"
OFFLOAD_OPTIMIZER_FAST_INIT = "fast_init"
