"""Tiled / memory-efficient linear layers for huge weight matrices.

Capability parity with the reference's ZeRO memory helpers:

- ``TiledLinear`` (reference `zero/tiling.py:26`): split one enormous
  Linear into an ``in_splits x out_splits`` grid of tiles so sharded
  training only ever materializes one tile at a time. The torch version
  builds a grid of `nn.Linear` submodules and loops; here the tiles are a
  single stacked ``(in_splits, out_splits, in_tile, out_tile)`` array —
  one leaf GSPMD can shard along the leading tile axes, with the compute
  expressed as a ``lax.scan`` over input tiles so XLA materializes (and,
  under ZeRO-3-style sharding, all-gathers) only one tile slab per step.

- ``memory_efficient_linear`` (reference `zero/linear.py:29`,
  ``LinearFunctionForZeroStage3``): a linear whose autograd context does
  not pin the gathered weight. The torch version hand-rolls an
  autograd.Function storing tensor *ids*; the JAX-native mechanism is
  ``jax.checkpoint`` with a policy that refuses to save any residual, so
  the backward pass re-gathers the (sharded-at-rest) weight instead of
  keeping the gathered copy alive between forward and backward.
"""

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


class TiledLinear:
    """A Linear stored as a grid of tiles.

    Parameters are a dict ``{"weight": (in_splits, out_splits, in_tile,
    out_tile), "bias": (out_features,)}``; ragged dimensions are
    zero-padded up to the tile grid (padding contributes nothing to the
    matmul and receives zero gradient).
    """

    def __init__(self, in_features, out_features, bias=True,
                 in_splits=1, out_splits=1):
        if in_splits < 1 or out_splits < 1:
            raise ValueError("in_splits/out_splits must be >= 1")
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.in_tile = -(-in_features // in_splits)
        self.out_tile = -(-out_features // out_splits)
        self.use_bias = bias

    def init_params(self, rng, dtype=jnp.float32):
        scale = 1.0 / math.sqrt(self.in_features)
        w = jax.random.uniform(
            rng, (self.in_splits, self.out_splits, self.in_tile,
                  self.out_tile),
            dtype, minval=-scale, maxval=scale)
        # Zero the padding rows/cols so padded inputs can't leak through.
        w = self._mask_padding(w)
        params = {"weight": w}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.out_features,), dtype)
        return params

    def _mask_padding(self, w):
        pad_in = self.in_splits * self.in_tile - self.in_features
        pad_out = self.out_splits * self.out_tile - self.out_features
        if pad_in:
            mask = (np.arange(self.in_tile) <
                    self.in_tile - pad_in)  # only last tile is ragged
            w = w.at[-1].multiply(mask[None, :, None].astype(w.dtype))
        if pad_out:
            mask = np.arange(self.out_tile) < self.out_tile - pad_out
            w = w.at[:, -1].multiply(mask[None, None, :].astype(w.dtype))
        return w

    def from_dense(self, weight, bias=None):
        """Pack a dense ``(in, out)`` weight into tile-grid params."""
        weight = jnp.asarray(weight)
        pad_in = self.in_splits * self.in_tile - self.in_features
        pad_out = self.out_splits * self.out_tile - self.out_features
        w = jnp.pad(weight, ((0, pad_in), (0, pad_out)))
        w = w.reshape(self.in_splits, self.in_tile,
                      self.out_splits, self.out_tile).transpose(0, 2, 1, 3)
        params = {"weight": w}
        if self.use_bias:
            params["bias"] = (jnp.zeros((self.out_features,), weight.dtype)
                              if bias is None else jnp.asarray(bias))
        return params

    def to_dense(self, params):
        w = params["weight"].transpose(0, 2, 1, 3).reshape(
            self.in_splits * self.in_tile, self.out_splits * self.out_tile)
        return w[:self.in_features, :self.out_features]

    def apply(self, params, x):
        """``y = x @ W + b`` scanning over input-tile slabs.

        The scan carries the accumulator; each step touches one
        ``(out_splits, in_tile, out_tile)`` slab, which is the only piece
        of the weight XLA must have resident (or gathered) at that step.
        """
        w = params["weight"]
        lead = x.shape[:-1]
        pad_in = self.in_splits * self.in_tile - self.in_features
        xp = jnp.pad(x.reshape(-1, self.in_features), ((0, 0), (0, pad_in)))
        xt = xp.reshape(-1, self.in_splits, self.in_tile)

        def step(acc, slab):
            xi, wi = slab  # (N, in_tile), (out_splits, in_tile, out_tile)
            acc = acc + jnp.einsum("ni,oij->noj", xi, wi,
                                   preferred_element_type=acc.dtype)
            return acc, None

        n = xt.shape[0]
        acc0 = jnp.zeros((n, self.out_splits, self.out_tile),
                         jnp.promote_types(x.dtype, jnp.float32))
        acc, _ = lax.scan(step, acc0,
                          (xt.transpose(1, 0, 2), w))
        y = acc.reshape(n, self.out_splits * self.out_tile)
        y = y[:, :self.out_features].astype(x.dtype)
        if self.use_bias and "bias" in params:
            y = y + params["bias"]
        return y.reshape(*lead, self.out_features)


def memory_efficient_linear(params, x):
    """Linear that rematerializes in backward instead of saving residuals.

    Equivalent of the reference's ``LinearFunctionForZeroStage3``
    (`zero/linear.py:29`): under ZeRO-3-style sharding the weight is
    sharded at rest and gathered for use; ``jax.checkpoint`` with
    ``nothing_saveable`` guarantees the gathered weight (and the input
    activation) are not pinned between forward and backward — backward
    re-gathers/recomputes.
    """

    @functools.partial(jax.checkpoint,
                       policy=jax.checkpoint_policies.nothing_saveable)
    def _linear(params, x):
        y = x @ params["weight"]
        if "bias" in params and params["bias"] is not None:
            y = y + params["bias"]
        return y

    return _linear(params, x)
