"""Tiered parameter/optimizer offload on the explicit schedule —
ZeRO-Infinity for TPU (arXiv:2104.07857 + 2101.06840), composed with the
PR 11 explicit-dataflow ZeRO-3 substrate (`parallel/schedule.py`).

Where the legacy layer-streamed executor (`param_offload.py`) runs one
jitted *segment* at a time with depth-1 prefetch and per-segment host
grads, this executor runs the explicit schedule's *group programs*:

- parameters rest off-device as **rank-major rows** — per remat/prefetch
  group, one ``[g, world * S]`` buffer in the `pack_plan_rows` layout —
  in host DRAM (the store of record) or NVMe (via the crash-consistent
  `AsyncPartitionedParameterSwapper` staging path);
- the host loop streams rows to HBM with **double-buffered prefetch
  issued ``prefetch_depth`` layers ahead** of the group the device is
  computing: `jax.device_put` is async, compute dispatch is async, and
  uploaded rows are **donated** into their consuming program, so the
  h2d wire rides under the previous group's matmuls (the discipline the
  async-checkpoint writer and the MoE a2a-overlap path proved);
- inside each group program the rows all-gather (bucketed, depth layers
  ahead — `make_group_body`, the SAME body the in-jit explicit schedule
  scans) and the backward's gather transposes **reduce-scatter each
  gradient row to its owner shard** before it ever leaves the device;
- gradient rows stream back device→host asynchronously (the d2h of
  group i overlaps the backward of group i-1) and accumulate in fp32;
- the **Adam update runs tier-side** on the engine's host-resident fp32
  masters/moments (`_init_host_state` — DRAM, or NVMe via the pipelined
  optimizer swapper), and only the fresh compute-dtype parameter rows
  ever cross back over the wire.

Peak HBM: one group's gathered params + the group-boundary activations
+ at most two in-flight gradient rows — host memory is the model-size
bound, which is the ZeRO-Infinity capacity story.

Telemetry: upload waits land in the `param_gather` span (the goodput
``param_wait`` bucket) and the runner feeds
``Train/Offload/{prefetch_stall_ms,bytes_h2d,bytes_d2h}`` scalars.
"""

import math
import re
from collections import deque

import numpy as np

import jax

from ...parallel.schedule import pack_plan_rows, unpack_plan_row
from ..telemetry import aot_compile_with_flops


def _safe_name(key):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(key))


class TieredPrograms:
    """Container for a model's tiered-offload step programs (built by
    ``model.build_tiered_offload_step``; see `models/gpt_neox.py`).

    plans: {"embed": LayerPlan, "block": LayerPlan, "final_ln":
        LayerPlan, "embed_out": LayerPlan|None} — all-flat-sharded
        `offload_layer_plan`s (one per segment kind).
    group_sizes: layers per block group, in order.
    tied: True when the LM head reuses the embedding row (its gradient
        accumulates into the embed segment).
    embed_fwd(row, tokens) -> x;  embed_grad(row, tokens, dx) -> grow
    group_fwd[g](rows, x) -> x;   group_grad[g](rows, x_in, ct)
        -> (ct_in, grows)  — grows arrive reduce-scattered (the gather
        transposes), assembled to the global rank-major row layout.
    head_loss(row_ln, row_we, x, labels) -> loss (dp-mean)
    head_grad(row_ln, row_we, x, labels, scale)
        -> (loss, dx, grow_ln, grow_we)
    split_batch(batch) -> (tokens, labels)
    """

    def __init__(self, plans, group_sizes, tied, embed_fwd, embed_grad,
                 group_fwd, group_grad, head_loss, head_grad,
                 split_batch):
        self.plans = plans
        self.group_sizes = list(group_sizes)
        self.tied = bool(tied)
        self.embed_fwd = embed_fwd
        self.embed_grad = embed_grad
        self.group_fwd = dict(group_fwd)
        self.group_grad = dict(group_grad)
        self.head_loss = head_loss
        self.head_grad = head_grad
        self.split_batch = split_batch


class OffloadStats:
    """Per-step offload counters the engine drains into telemetry."""

    __slots__ = ("prefetch_stall_s", "bytes_h2d", "bytes_d2h", "flops")

    def __init__(self):
        self.reset()

    def reset(self):
        self.prefetch_stall_s = 0.0
        self.bytes_h2d = 0
        self.bytes_d2h = 0
        self.flops = 0.0

    def drain(self):
        out = {"prefetch_stall_s": self.prefetch_stall_s,
               "bytes_h2d": self.bytes_h2d, "bytes_d2h": self.bytes_d2h,
               "flops": self.flops}
        self.reset()
        return out


class _CountingProgram:
    """Jitted program wrapper: on first call (per program) AOT-compiles
    to harvest `cost_analysis` flops — the executable IS what runs, so
    the harvest is free — then adds the program's flops to the shared
    stats at every dispatch. With counting off it is a transparent
    passthrough (no AOT, no overhead)."""

    def __init__(self, jitted, stats, count_flops):
        self._fn = jitted
        self._jitted = jitted
        self._stats = stats
        self._count = count_flops
        self._flops = None
        self._compiled_once = False

    def __call__(self, *args):
        if self._count and not self._compiled_once:
            self._compiled_once = True
            fn, flops = aot_compile_with_flops(
                self._jitted, args, rebuild=lambda: self._jitted)
            self._fn, self._flops = fn, flops
        if self._flops:
            self._stats.flops += self._flops
        return self._fn(*args)


class TieredRowStore:
    """The off-device row store: {key: rank-major row array}. DRAM tier
    holds writable numpy rows (the store of record); NVMe tier keeps one
    crash-consistently staged swap file per key, DRAM holding only
    shape/dtype templates plus the pooled aio buffers."""

    def __init__(self, swapper=None):
        self.swapper = swapper
        self._rows = {}           # DRAM tier
        self._templates = {}      # NVMe tier: key -> (shape, dtype)
        self._inflight = set()    # NVMe reads issued

    def put(self, key, row, async_op=True):
        if self.swapper is None:
            self._rows[key] = row
            return
        self._templates[key] = (row.shape, row.dtype)
        self.swapper.swap_out(_safe_name(key),
                              np.ascontiguousarray(row).reshape(-1)
                              .view(np.uint8))
        if not async_op:
            self.swapper.synchronize_writes()

    def synchronize(self):
        if self.swapper is not None:
            self.swapper.synchronize_writes()

    def prefetch(self, key):
        """NVMe: issue the aio read now (non-blocking); DRAM: no-op."""
        if self.swapper is None or key in self._inflight:
            return
        self.swapper.swap_in([_safe_name(key)], async_op=True)
        self._inflight.add(key)

    def fetch(self, key):
        """Host row bytes for `key` — ALWAYS a private copy: device_put
        can be zero-copy on the CPU backend (an aliased upload would
        read whatever the store holds when XLA lazily consumes it), and
        the NVMe tier's pooled aio buffer is reused for the next
        read."""
        if self.swapper is None:
            return np.array(self._rows[key])
        self.prefetch(key)
        self.swapper.synchronize_reads()
        self._inflight.discard(key)
        views = self.swapper.swap_in([_safe_name(key)], async_op=False)
        shape, dtype = self._templates[key]
        out = np.array(views[_safe_name(key)].view(dtype)).reshape(shape)
        self.swapper.release([_safe_name(key)])
        return out

    def keys(self):
        return (self._rows if self.swapper is None
                else self._templates).keys()


class _UploadWindow:
    """One micro-batch's double-buffered upload pipeline over a linear
    schedule of (slot, key) uploads: `ensure(i)` keeps `depth` uploads
    issued beyond slot i (async `device_put`s the latency-hiding
    scheduler overlaps with compute), `take(i)` hands slot i's device
    rows over — timing any residual wait as a prefetch stall in the
    `param_gather` span (the goodput ``param_wait`` bucket)."""

    def __init__(self, order, store, shardings, depth, stats, telemetry):
        self.order = list(order)
        self.store = store
        self.shardings = shardings
        self.depth = max(1, int(depth))
        self.stats = stats
        self.telemetry = telemetry
        self._slots = {}
        self._issued = 0

    def ensure(self, idx):
        hi = min(len(self.order), idx + 1 + self.depth)
        # NVMe reads for the whole lookahead go out first: the aio
        # engine overlaps them with the device_puts below
        for j in range(self._issued, hi):
            self.store.prefetch(self.order[j])
        while self._issued < hi:
            j = self._issued
            key = self.order[j]
            row = self.store.fetch(key)
            self._slots[j] = jax.device_put(row, self.shardings(key))
            self.stats.bytes_h2d += row.nbytes
            self._issued += 1

    def take(self, idx):
        import time
        self.ensure(idx)
        arr = self._slots.pop(idx)
        ready = True
        try:
            ready = arr.is_ready()
        except Exception:  # noqa: BLE001 - backends without is_ready
            pass
        if not ready:
            # the compute stream is about to stall on this upload: that
            # wait IS lost goodput — time it under param_gather
            with self.telemetry.span("param_gather"):
                t0 = time.perf_counter()
                jax.block_until_ready(arr)
                self.stats.prefetch_stall_s += time.perf_counter() - t0
        return arr


class TieredOffloadRunner:
    """Owns the host row store, the upload pipeline and the per-group
    program driver for the tiered-offload executor. The ENGINE keeps
    owning the fp32 masters/moments (`_init_host_state` — leaf-major,
    so checkpoints ride the existing host-offload manifest payload
    bit-exactly) and the Adam step; the runner converts between the
    leaf world and the row world at the step boundary."""

    def __init__(self, programs, host_params, compute_dtype, mesh,
                 data_axis, prefetch_depth, telemetry, nvme=None,
                 count_flops=False):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.programs = programs
        self.compute_dtype = np.dtype(compute_dtype)
        self.telemetry = telemetry
        self.stats = OffloadStats()
        self._row_sh = NamedSharding(mesh, P(data_axis))
        self._rows_sh = NamedSharding(mesh, P(None, data_axis))
        self._scale_sh = NamedSharding(mesh, P())
        self.world = int(mesh.shape[data_axis])

        leaves, treedef = jax.tree_util.tree_flatten(host_params)
        self.n_leaves = len(leaves)
        self._leaf_shapes = [np.shape(l) for l in leaves]
        idx_tree = jax.tree_util.tree_unflatten(
            treedef, list(range(self.n_leaves)))

        def ids_of(sub):
            return [int(i) for i in jax.tree_util.tree_leaves(sub)]

        G = len(programs.group_sizes)
        self.group_keys = [("blocks", gi) for gi in range(G)]
        # per-key: (plan, [leaf-id list per row]) — groups carry one id
        # list per layer, the outer segments exactly one
        self._layout = {}
        self._layout["embed"] = (programs.plans["embed"],
                                 [ids_of({"wte": idx_tree["embed"]["wte"]})])
        self._layout["final_ln"] = (programs.plans["final_ln"],
                                    [ids_of(idx_tree["final_ln"])])
        if not programs.tied:
            self._layout["embed_out"] = (
                programs.plans["embed_out"],
                [ids_of({"wte": idx_tree["embed_out"]["wte"]})])
        li = 0
        for gi, g in enumerate(programs.group_sizes):
            self._layout[("blocks", gi)] = (
                programs.plans["block"],
                [ids_of(idx_tree["blocks"][li + j]) for j in range(g)])
            li += g
        self._we_key = "embed" if programs.tied else "embed_out"

        # depth in GROUPS: prefetch_depth is a layers-ahead knob; the
        # host pipeline's upload unit is one group — keep at least the
        # double buffer
        g0 = max(1, programs.group_sizes[0] if programs.group_sizes else 1)
        self.depth = max(1, math.ceil(max(1, int(prefetch_depth)) / g0))

        swapper = None
        if nvme is not None:
            # NVMe rows ride the crash-consistently staged swapper; the
            # pool is sized to the fattest row, and holds at least the
            # whole prefetch window (depth+1 reads can be in flight,
            # each pinning one pooled buffer until its fetch) plus one
            # spare — a deep prefetch_depth must not exhaust the pool
            # mid-step
            from ..swap_tensor.partitioned_param_swapper import \
                AsyncPartitionedParameterSwapper
            max_row = max(
                len(per_row) * plan.shard_size * self.world
                * self.compute_dtype.itemsize
                for plan, per_row in self._layout.values())
            swapper = AsyncPartitionedParameterSwapper(
                nvme_path=nvme["nvme_path"],
                buffer_count=max(3, int(nvme.get("buffer_count", 3)),
                                 self.depth + 2),
                buffer_size=max_row, aio_config=nvme.get("aio_config"),
                dtype=np.uint8)
        self.store = TieredRowStore(swapper=swapper)

        # initial spill: pack every segment's host leaves into rows
        flat = leaves
        for key, (plan, per_row_ids) in self._layout.items():
            self.store.put(key, self._pack_key(
                key, {lid: np.asarray(flat[lid], self.compute_dtype)
                      for ids in per_row_ids for lid in ids}),
                async_op=True)
        self.store.synchronize()

        wrap = lambda fn: _CountingProgram(fn, self.stats, count_flops)  # noqa: E731
        p = programs
        self._embed_fwd = wrap(p.embed_fwd)
        self._embed_grad = wrap(p.embed_grad)
        self._head_loss = wrap(p.head_loss)
        self._head_grad = wrap(p.head_grad)
        self._group_fwd = {g: wrap(fn) for g, fn in p.group_fwd.items()}
        self._group_grad = {g: wrap(fn) for g, fn in p.group_grad.items()}

        self._grad_rows = {}
        self._pending = deque()

    # -- layout conversion -------------------------------------------------

    def _pack_key(self, key, leaf_arrays):
        """{leaf_id: natural array} -> this key's row buffer."""
        plan, per_row_ids = self._layout[key]
        rows = [pack_plan_rows(
            plan, [np.asarray(leaf_arrays[lid], self.compute_dtype)
                   .reshape(self._leaf_shapes[lid]) for lid in ids])
            for ids in per_row_ids]
        return rows[0] if len(rows) == 1 and key not in self.group_keys \
            else np.stack(rows)

    def _unpack_grads(self, key, grows):
        """Accumulated fp32 grad row(s) of one key -> {leaf_id: flat
        fp32 grad} (tied leaves already summed at the row level)."""
        plan, per_row_ids = self._layout[key]
        mat = grows if grows.ndim == 2 else grows[None]
        out = {}
        for row, ids in zip(mat, per_row_ids):
            for lid, leaf in zip(ids, unpack_plan_row(plan, row)):
                out[lid] = np.asarray(leaf, np.float32).reshape(-1)
        return out

    # -- gradient harvest --------------------------------------------------

    def _harvest_later(self, key, dev):
        """Queue one grad row's d2h: start the async copy now, drain it
        after the NEXT backward dispatch (so the transfer rides under
        compute instead of serializing the host loop)."""
        try:
            dev.copy_to_host_async()
        except AttributeError:
            pass
        self._pending.append((key, dev))
        while len(self._pending) > 1:
            self._drain_one()

    def _drain_one(self):
        key, dev = self._pending.popleft()
        # count the bytes the WIRE moved (compute dtype), not the fp32
        # accumulator they widen into — else bf16 runs report 2x d2h
        self.stats.bytes_d2h += dev.nbytes
        arr = np.asarray(jax.device_get(dev), np.float32)
        acc = self._grad_rows.get(key)
        if acc is None:
            self._grad_rows[key] = np.array(arr) if not arr.flags.writeable \
                else arr
        else:
            acc += arr

    def _flush_harvest(self):
        while self._pending:
            self._drain_one()

    # -- driver ------------------------------------------------------------

    def begin_step(self):
        self._grad_rows = {}
        self._pending.clear()

    def _forward(self, tokens, win):
        x = self._embed_fwd(win.take(0), tokens)
        stash = []
        for i, g in enumerate(self.programs.group_sizes):
            stash.append(x)
            x = self._group_fwd[g](win.take(1 + i), x)
        return x, stash

    def fwd_bwd_micro(self, batch, scale):
        """One micro-batch: streamed forward (group-boundary activations
        stashed), head loss+grad, reverse streamed backward with re-
        uploaded rows, grad rows accumulated host-side. Returns the
        device loss scalar (do NOT float() it per micro — host sync)."""
        tokens, labels = self.programs.split_batch(batch)
        G = len(self.programs.group_sizes)
        order = (["embed"] + self.group_keys                 # forward
                 + ["final_ln", self._we_key]                # head
                 + list(reversed(self.group_keys)) + ["embed"])  # backward
        win = _UploadWindow(order, self.store, self._key_sharding,
                            self.depth, self.stats, self.telemetry)
        x, stash = self._forward(tokens, win)
        scale_dev = jax.device_put(np.float32(scale), self._scale_sh)
        loss, dx, g_ln, g_we = self._head_grad(
            win.take(G + 1), win.take(G + 2), x, labels, scale_dev)
        self._harvest_later("final_ln", g_ln)
        self._harvest_later(self._we_key, g_we)
        for i in range(G - 1, -1, -1):
            g = self.programs.group_sizes[i]
            slot = G + 3 + (G - 1 - i)
            dx, grows = self._group_grad[g](win.take(slot), stash.pop(),
                                            dx)
            self._harvest_later(("blocks", i), grows)
        g_e = self._embed_grad(win.take(2 * G + 3), tokens, dx)
        self._harvest_later("embed", g_e)
        self._flush_harvest()
        return loss

    def eval_loss(self, batch):
        tokens, labels = self.programs.split_batch(batch)
        G = len(self.programs.group_sizes)
        order = ["embed"] + self.group_keys + ["final_ln", self._we_key]
        win = _UploadWindow(order, self.store, self._key_sharding,
                            self.depth, self.stats, self.telemetry)
        x, _ = self._forward(tokens, win)
        return self._head_loss(win.take(G + 1), win.take(G + 2), x,
                               labels)

    def _key_sharding(self, key):
        return self._rows_sh if key in self.group_keys else self._row_sh

    # -- step-boundary conversions (engine's host Adam owns the update) ----

    def collect_leaf_grads(self, coef):
        """Accumulated grad rows -> per-leaf natural flat fp32 grads in
        tree_leaves order, scaled by `coef` (1 / (gas * world); the loss
        scale divides in the engine's shared host step). psum_scatter
        summed per-rank contributions of per-rank-MEAN losses, so /world
        recovers the dp-mean gradient."""
        flats = [None] * self.n_leaves
        for key, grows in self._grad_rows.items():
            for lid, flat in self._unpack_grads(key, grows).items():
                flats[lid] = flat * coef
        missing = [i for i, f in enumerate(flats) if f is None]
        if missing:
            raise RuntimeError(
                f"tiered offload step produced no gradients for leaves "
                f"{missing} — the segment layout lost track of them")
        return flats

    def publish_updated_leaves(self, emitted):
        """{leaf_id: fresh compute-dtype flat} from the host Adam step →
        repacked rows written back to the store (the ONLY h2d-relevant
        state the update touches: masters/moments never leave the
        host)."""
        for key, (plan, per_row_ids) in self._layout.items():
            arrs = {lid: emitted[lid] for ids in per_row_ids
                    for lid in ids}
            self.store.put(key, self._pack_key(key, arrs), async_op=True)
        self.store.synchronize()

    # -- natural-tree access (checkpoints / user surfaces) -----------------

    def leaves_natural(self):
        """All params as natural compute-dtype numpy leaves (flatten
        order). Transiently model-sized on host — checkpoint/export
        only."""
        leaves = [None] * self.n_leaves
        for key, (plan, per_row_ids) in self._layout.items():
            rows = self.store.fetch(key)
            mat = rows if key in self.group_keys else rows[None]
            for row, ids in zip(mat, per_row_ids):
                for lid, leaf in zip(ids, unpack_plan_row(plan, row)):
                    leaves[lid] = leaf
        return leaves

    def write_natural(self, tree_leaves_list):
        """Inverse of `leaves_natural`: replace the whole store from
        natural leaves (checkpoint restore, gathered_parameters
        write-back)."""
        for key, (plan, per_row_ids) in self._layout.items():
            arrs = {lid: np.asarray(tree_leaves_list[lid],
                                    self.compute_dtype)
                    for ids in per_row_ids for lid in ids}
            self.store.put(key, self._pack_key(key, arrs), async_op=True)
        self.store.synchronize()
