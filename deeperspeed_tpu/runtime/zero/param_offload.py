"""ZeRO-Infinity parameter offload: layer-streamed training
(reference: `deepspeed/runtime/zero/stage3.py:916-935` NVMe param path +
`swap_tensor/partitioned_param_swapper.py:36` +
`zero/partition_parameters.py:610-744`).

The reference keeps ZeRO-3 param shards on CPU/NVMe and round-trips each
submodule's params through the `AsyncPartitionedParameterSwapper` during
forward/backward, so device memory holds only the live layers. The same
capability on TPU cannot live inside one jitted step (a jit consumes its
whole input pytree up front), so the engine switches to a *layer-streamed*
executor:

- params rest on host DRAM (`offload_param.device: cpu`) or NVMe
  (`device: nvme`, via the async swapper) in the compute dtype;
- forward runs one jitted segment at a time (embed → blocks → LM head),
  uploading each segment's params just before use (async `device_put`
  prefetch of segment k+1 overlaps segment k's compute — the reference's
  `PrefetchCoordinator`) and dropping them after;
- backward re-uploads segments in reverse, recomputes each segment's
  forward under `jax.vjp` (layer-granular activation checkpointing), and
  ships the segment's grads straight to the host optimizer buffers;
- the update is the existing ZeRO-Offload host tier (native CPU Adam,
  optionally swapping optimizer state to NVMe), which writes fresh
  compute-dtype params back into the host/NVMe store.

Peak HBM = one segment's params + boundary activations — the
100B+-params/chip ladder rung of ZeRO-Infinity, bounded by DRAM/NVMe
instead of HBM.

Models opt in by exposing ``stream_plan()`` (see `StreamPlan`;
`models/gpt_neox.py` implements it).
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class StreamPlan:
    """A model's layer-streaming decomposition.

    segments: ordered ``(name, select_fn)`` where ``select_fn(params)``
        returns the segment's param subtree (views — leaves may be shared
        across segments, e.g. tied embeddings; gradient accumulation by
        leaf identity sums the tied contributions exactly like the
        reference's tied-weight allreduce).
    forward: ``{name: fn(seg_params, carry, batch, rng) -> carry}``; the
        first segment receives ``carry=None`` (it reads the batch), the
        LAST segment must return the scalar loss.
    kinds: optional ``{name: kind}``; segments sharing a kind share one
        compiled forward/backward (the uniform transformer blocks).
    """

    def __init__(self, segments: List[Tuple[str, Callable]],
                 forward: Dict[str, Callable],
                 kinds: Optional[Dict[str, str]] = None):
        self.segments = list(segments)
        self.forward = dict(forward)
        self.kinds = dict(kinds or {})
        for name, _ in self.segments:
            self.kinds.setdefault(name, name)

    def kind(self, name):
        return self.kinds[name]


class LazyLeaf:
    """Deferred parameter initializer for beyond-DRAM models (the
    reference's `zero.Init`-with-immediate-NVMe-spill,
    `zero/partition_parameters.py:610-744`): carries shape/dtype so
    sharding rules and templates can be computed without materializing;
    the engine realizes it one segment at a time during the initial
    spill and frees it immediately — the full tree never exists in
    DRAM."""

    __slots__ = ("shape", "dtype", "init_fn")

    def __init__(self, shape, dtype, init_fn):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.init_fn = init_fn

    def __call__(self):
        out = np.asarray(self.init_fn(), self.dtype)
        if out.shape != self.shape:
            raise ValueError(
                f"LazyLeaf init_fn returned {out.shape}, "
                f"declared {self.shape}")
        return out


def _flatten_bytes(subtree):
    """Concatenate a subtree's leaves into one uint8 buffer (the on-disk
    segment layout)."""
    leaves = jax.tree_util.tree_leaves(subtree)
    return np.concatenate([np.asarray(l).ravel().view(np.uint8)
                           for l in leaves])


class ParamStreamCoordinator:
    """Owns the off-device param store and the device-side streaming
    window (fetch/prefetch/release), mirroring the reference's
    `PartitionedParameterCoordinator` (`stage3.py:287`).

    Host ("cpu") tier: segments are views into the engine's host param
    tree; fetch = async `device_put`. NVMe tier: each segment is one flat
    file managed by `AsyncPartitionedParameterSwapper`; fetch = async aio
    read into a pooled buffer, then `device_put`.
    """

    def __init__(self, plan, host_params, compute_dtype, sharding=None,
                 swapper=None, spill=True):
        self.plan = plan
        self.compute_dtype = compute_dtype
        self.sharding = sharding
        self.swapper = swapper
        self._device: Dict[str, Any] = {}
        self._nvme_inflight: Dict[str, Any] = {}
        # Per-segment shape/dtype templates — the ONLY per-param host
        # metadata the NVMe tier keeps resident.
        self._templates: Dict[str, Any] = {}
        for name, sel in plan.segments:
            sub = sel(host_params)
            leaves, treedef = jax.tree_util.tree_flatten(
                sub, is_leaf=lambda x: isinstance(x, LazyLeaf))
            self._templates[name] = (
                treedef, [(tuple(l.shape), np.dtype(l.dtype))
                          for l in leaves])
        if swapper is None:
            self._host: Optional[Dict[str, Any]] = {
                name: sel(host_params) for name, sel in plan.segments}
        else:
            # NVMe is the store of record (reference
            # `partitioned_param_swapper.py:36,238-304`): the segments
            # spill once (here, or segment-by-segment by the engine when
            # `spill=False` — the lazy-init path), then DRAM holds no
            # param mirror, only the templates above.
            self._host = None
            if spill:
                for name, sel in plan.segments:
                    self.swapper.swap_out(
                        name, _flatten_bytes(sel(host_params)))
                swapper.synchronize_writes()

    def segment_nbytes(self, name):
        _, specs = self._templates[name]
        return sum(int(np.prod(s)) * dt.itemsize for s, dt in specs)

    # -- NVMe segment <-> flat-file helpers --------------------------------

    def _seg_from_flat(self, name, flat_u8):
        """Rebuild the segment subtree from raw bytes. COPIES out of the
        pooled aio buffer: `device_put` can be zero-copy (the CPU backend
        aliases host memory), so views into the pool would silently
        change when the buffer is reused for the next read."""
        treedef, specs = self._templates[name]
        out, off = [], 0
        for shape, dt in specs:
            nbytes = int(np.prod(shape)) * dt.itemsize
            out.append(np.array(
                flat_u8[off:off + nbytes].view(dt)).reshape(shape))
            off += nbytes
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- host-side segment IO (checkpoints, GatheredParameters) ------------

    def read_segment_host(self, name):
        """The segment's params as host numpy arrays (NVMe: synchronous
        read through the pooled aio buffers)."""
        if self.swapper is None:
            return self._host[name]
        views = self.swapper.swap_in([name], async_op=False)
        sub = self._seg_from_flat(name, views[name])
        self.swapper.release([name])
        return sub

    def write_segment(self, name, subtree=None, flat_u8=None,
                      async_op=True):
        """Replace a segment's stored params (NVMe tier; the cpu tier's
        leaves are shared views the caller mutates in place). Call
        `synchronize_writes` after a batch of writes."""
        if self.swapper is None:
            return
        if flat_u8 is None:
            flat_u8 = _flatten_bytes(subtree)
        self.swapper.swap_out(name, flat_u8)
        if not async_op:
            self.swapper.synchronize_writes()

    def synchronize_writes(self):
        if self.swapper is not None:
            self.swapper.synchronize_writes()

    # -- streaming window --------------------------------------------------

    def _upload(self, subtree):
        # np.array (copy), not asarray: device_put can be ZERO-COPY (the
        # CPU backend aliases host memory), and the host optimizer
        # mutates the store leaves in place every step — an aliased
        # "device" segment would silently change under XLA's lazy reads.
        def put(x):
            x = np.array(x)
            return jax.device_put(x, self.sharding) \
                if self.sharding is not None else jax.device_put(x)

        return jax.tree_util.tree_map(put, subtree)

    def prefetch(self, name):
        """Start moving a segment toward the device without blocking:
        `device_put` is async; NVMe reads go through the aio thread
        pool."""
        if name is None or name in self._device:
            return
        if self.swapper is None:
            self._device[name] = self._upload(self._host[name])
        elif name not in self._nvme_inflight:
            views = self.swapper.swap_in([name], async_op=True)
            self._nvme_inflight[name] = views[name]

    def fetch(self, name):
        """Device subtree for a segment, completing any inflight read."""
        if name in self._device:
            return self._device[name]
        if self.swapper is not None:
            if name not in self._nvme_inflight:
                self.prefetch(name)
            self.swapper.synchronize_reads()
            flat_u8 = self._nvme_inflight.pop(name)
            # _seg_from_flat copies synchronously, so the pooled buffer
            # can be released right away
            self._device[name] = self._upload(
                self._seg_from_flat(name, flat_u8))
            self.swapper.release([name])
        else:
            self._device[name] = self._upload(self._host[name])
        return self._device[name]

    def release(self, name):
        """Drop the device copy (XLA frees it once consumers finish)."""
        self._device.pop(name, None)

    def publish_host_update(self, names=None):
        """cpu tier: no-op (the store leaves are shared views the host
        optimizer mutated in place). NVMe tier: there is no host mirror
        to publish FROM — callers must `write_segment` the fresh bytes
        they produced; reaching here is a stale-caller bug."""
        if self.swapper is None:
            return
        raise RuntimeError(
            "NVMe param tier has no host mirror: write updated segments "
            "with write_segment(name, subtree) instead of "
            "publish_host_update()")


class GradSpillStore:
    """Per-segment fp32 gradient accumulation on NVMe (reference: the
    ZeRO-Infinity gradient swap path, `swap_tensor/optimizer_utils.py`).

    During the streamed backward, each segment's gradients are added into
    a per-segment flat fp32 file: DRAM holds at most one segment's
    gradients at a time, so accumulation memory — like params and
    optimizer state — is bounded by NVMe, not DRAM. Tied leaves appear
    in several segments' files as PARTIAL contributions; `leaf_slices`
    lets the optimizer sum them at step time."""

    def __init__(self, swapper, plan, seg_leaf_ids):
        self.swapper = swapper
        self.plan = plan
        self.seg_leaf_ids = dict(seg_leaf_ids)
        self._written = set()
        # {segment: [(leaf_id, start_f32, size_f32)]}
        self.leaf_slices: Dict[str, List[Tuple[int, int, int]]] = {}

    def begin_step(self):
        self._written.clear()

    def add(self, name, dparams):
        """Accumulate one micro-batch's segment grads (read-modify-write
        after the first micro)."""
        leaves = jax.tree_util.tree_leaves(dparams)
        flats = [np.asarray(jax.device_get(g), np.float32).ravel()
                 for g in leaves]
        if name not in self.leaf_slices:
            slices, off = [], 0
            for lid, f in zip(self.seg_leaf_ids[name], flats):
                slices.append((lid, off, f.size))
                off += f.size
            self.leaf_slices[name] = slices
        total = np.concatenate(flats)
        if name in self._written:
            views = self.swapper.swap_in([name], async_op=False)
            total = total + views[name].view(np.float32)
            self.swapper.release([name])
        self.swapper.swap_out(name, total.view(np.uint8))
        self.swapper.synchronize_writes()
        self._written.add(name)

    def read(self, name):
        """The segment's accumulated flat fp32 grads (a copy)."""
        views = self.swapper.swap_in([name], async_op=False)
        out = np.array(views[name].view(np.float32))
        self.swapper.release([name])
        return out


def make_segment_fns(plan, donate_carry=True, count_flops=False):
    """Compiled forward/backward per segment *kind*.

    fwd(p, carry, batch, rng) -> carry
    bwd(p, carry, ct, batch, rng) -> (dparams, dcarry)
        recomputes the segment forward under `jax.vjp` (layer-granular
        remat) and pulls cotangents back to params and carry.

    Returns (fwd, bwd, stats): with ``count_flops`` each program is
    AOT-compiled at first call and its `cost_analysis` flops accumulate
    into ``stats.flops`` per dispatch, so the streamed tier can report
    MFU like the on-chip step variants (`stats` is an `OffloadStats`;
    a no-op accumulator when counting is off)."""
    from .offload_engine import OffloadStats, _CountingProgram

    stats = OffloadStats()
    fwd_jit, bwd_jit = {}, {}
    for name, _ in plan.segments:
        kind = plan.kind(name)
        if kind in fwd_jit:
            continue
        fn = plan.forward[name]

        fwd_jit[kind] = _CountingProgram(jax.jit(fn), stats, count_flops)

        def bwd(p, carry, ct, batch, rng, _fn=fn):
            if carry is None:
                out, vjp = jax.vjp(lambda p_: _fn(p_, None, batch, rng), p)
                (dp,) = vjp(ct)
                return dp, None
            out, vjp = jax.vjp(
                lambda p_, c_: _fn(p_, c_, batch, rng), p, carry)
            dp, dc = vjp(ct)
            return dp, dc

        bwd_jit[kind] = _CountingProgram(jax.jit(bwd), stats, count_flops)
    return fwd_jit, bwd_jit, stats


def segment_leaf_indices(plan, params):
    """{segment name: flat-leaf indices into tree_leaves(params)} — the
    bridge between per-segment gradients and the host optimizer's flat
    leaf list. Tied leaves appear in several segments with the SAME index,
    so host accumulation sums their gradients (tied-weight semantics)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    idx_tree = jax.tree_util.tree_unflatten(treedef,
                                            list(range(len(leaves))))
    return {name: [int(i) for i in jax.tree_util.tree_leaves(sel(idx_tree))]
            for name, sel in plan.segments}
