"""Contiguous host-buffer allocator with defragmentation.

Capability parity with the reference's ``ContiguousMemoryAllocator``
(`zero/contiguous_memory_allocator.py:9`), which hands out sub-tensors of
one preallocated flat buffer and compacts live blocks when fragmentation
blocks an allocation. On TPU the device side needs no such thing (XLA owns
HBM layout), but the host offload tier does: the NVMe/DRAM swappers keep
pinned staging buffers, and recycling them through one arena avoids both
allocator churn and fragmentation of the pinned region.

Blocks are addressed by integer id; ``get_tensor(id)`` returns the current
numpy view (views move on defrag, so holders re-fetch by id — the torch
reference instead mutates ``param.data`` in place via stored callbacks).
"""

import numpy as np


class ContiguousMemoryAllocator:
    def __init__(self, size, dtype=np.float32):
        self.buffer = np.zeros(int(size), dtype=dtype)
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        # offset -> length of free ranges; kept coalesced.
        self._free = {0: self.size}
        # block id -> (offset, length)
        self._blocks = {}
        self._next_id = 0
        self.total_free = self.size
        self.largest_contiguous = self.size

    # -- bookkeeping -------------------------------------------------------

    def _recompute_stats(self):
        self.total_free = sum(self._free.values())
        self.largest_contiguous = max(self._free.values(), default=0)

    def _coalesce(self):
        merged = {}
        for off in sorted(self._free):
            length = self._free[off]
            if merged:
                last_off = next(reversed(merged))
                if last_off + merged[last_off] == off:
                    merged[last_off] += length
                    continue
            merged[off] = length
        self._free = merged
        self._recompute_stats()

    # -- public api --------------------------------------------------------

    def allocate_tensor(self, numel):
        """Allocate a block of ``numel`` elements; returns its id.

        Defragments (compacts live blocks to the left) when no single free
        range fits but the total free space does.
        """
        numel = int(numel)
        if numel > self.total_free:
            raise MemoryError(
                f"arena exhausted: need {numel}, free {self.total_free}")
        if numel > self.largest_contiguous:
            self.defragment()
        for off in sorted(self._free):
            length = self._free[off]
            if length >= numel:
                del self._free[off]
                if length > numel:
                    self._free[off + numel] = length - numel
                self._recompute_stats()
                bid = self._next_id
                self._next_id += 1
                self._blocks[bid] = (off, numel)
                return bid
        raise MemoryError("defragmentation failed to produce a fit")

    def get_tensor(self, block_id):
        off, numel = self._blocks[block_id]
        return self.buffer[off:off + numel]

    def release_tensor(self, block_id):
        off, numel = self._blocks.pop(block_id)
        self._free[off] = numel
        self._coalesce()

    def defragment(self):
        """Compact live blocks to the start of the buffer (stable order)."""
        cursor = 0
        for bid in sorted(self._blocks, key=lambda b: self._blocks[b][0]):
            off, numel = self._blocks[bid]
            if off != cursor:
                # memmove semantics: ranges may overlap when shifting left.
                self.buffer[cursor:cursor + numel] = \
                    self.buffer[off:off + numel].copy()
                self._blocks[bid] = (cursor, numel)
            cursor += numel
        self._free = {cursor: self.size - cursor} if cursor < self.size else {}
        self._recompute_stats()

    def allocated(self):
        return self.size - self.total_free

    def print_allocation(self):  # pragma: no cover - debug aid
        live = {b: self._blocks[b] for b in sorted(self._blocks)}
        print(f"arena size={self.size} free={self.total_free} "
              f"largest_contiguous={self.largest_contiguous} blocks={live}")
