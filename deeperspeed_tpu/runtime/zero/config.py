"""ZeRO config objects (reference: `deepspeed/runtime/zero/config.py`,
`offload_config.py`).

Parsed into frozen dataclasses. The semantics on TPU:

- ``stage >= 1``: optimizer state carries a NamedSharding over the ``data``
  mesh axis.
- ``stage >= 2``: gradients are reduce-scattered (``psum_scatter``) instead of
  all-reduced.
- ``stage == 3``: parameters are sharded over ``data`` at rest and gathered
  per-layer by XLA (FSDP-style); prefetch/persistence knobs become latency
  hints.
"""

from dataclasses import dataclass, field
from typing import List, Optional

from ...parallel.schedule import SCHEDULE_MODES, ScheduleConfig
from ..config_utils import (DeepSpeedConfigError, as_int,
                            get_scalar_param, strict_bool,
                            strict_positive_int)
from . import constants as zc


def _parse_schedule_block(d, stage):
    """Parse + validate ``zero_optimization.schedule`` at checkpoint-block
    strictness (unknown keys / bad ranges raise at parse with the valid
    choices listed). This is the explicit-dataflow schedule surface
    (parallel/schedule.py): mode "explicit" swaps the ZeRO-3 hot loop
    from GSPMD sharding constraints to the shard_map collective schedule
    with layer-ahead prefetch; the knobs are shared with the pipeline
    comm-overlap path."""
    sched = d.get(zc.ZERO_OPTIMIZATION_SCHEDULE)
    if sched is None:
        sched = {}
    if not isinstance(sched, dict):
        # only None means "absent": a falsy wrong type ([] / 0 / false)
        # must not silently fall back to the gspmd default
        raise DeepSpeedConfigError(
            f"zero_optimization.{zc.ZERO_OPTIMIZATION_SCHEDULE} must be "
            f"a dict, got {sched!r}")
    known = {"mode", "prefetch_depth", "bucket_mb", "group_layers",
             "remat"}
    unknown = sorted(set(sched) - known)
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'zero_optimization.schedule' key(s) {unknown}; "
            f"valid keys: {sorted(known)}")
    mode = str(sched.get("mode", "gspmd"))
    if mode not in SCHEDULE_MODES:
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.mode must be one of "
            f"{list(SCHEDULE_MODES)} (gspmd = partitioner-scheduled "
            f"collectives, explicit = shard_map schedule with "
            f"layer-ahead prefetch), got {mode!r}")
    if mode == "explicit" and stage != 3:
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.mode \"explicit\" requires "
            f"stage 3 (it schedules the stage-3 parameter all-gathers); "
            f"got stage {stage}")
    prefetch_depth = as_int(sched.get("prefetch_depth", 1),
                            "zero_optimization.schedule.prefetch_depth")
    if prefetch_depth < 1:
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.prefetch_depth must be >= 1 "
            f"(layers gathered ahead of compute), got {prefetch_depth}")
    try:
        bucket_mb = float(sched.get("bucket_mb", 32))
    except (TypeError, ValueError):
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.bucket_mb must be a number "
            f"(max MB per all-gather bucket), got "
            f"{sched.get('bucket_mb')!r}")
    if not bucket_mb > 0:
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.bucket_mb must be > 0, got "
            f"{bucket_mb}")
    group_layers = as_int(sched.get("group_layers", 4),
                          "zero_optimization.schedule.group_layers")
    if group_layers < 1:
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.group_layers must be >= 1 "
            f"(layers per remat/prefetch group), got {group_layers}")
    remat = sched.get("remat", True)
    if not isinstance(remat, bool):
        raise DeepSpeedConfigError(
            f"zero_optimization.schedule.remat must be a boolean "
            f"(True = backward re-gathers params, False = keep gathered "
            f"buffers as residuals), got {remat!r}")
    return ScheduleConfig(mode=mode, prefetch_depth=prefetch_depth,
                          bucket_mb=bucket_mb, group_layers=group_layers,
                          remat=remat)


_OFFLOAD_DEVICES = (zc.OFFLOAD_CPU_DEVICE, zc.OFFLOAD_NVME_DEVICE)


def _check_offload_block(block, d, known):
    """Bring an offload sub-block to checkpoint-block parse strictness:
    it must be a dict, unknown keys raise with the valid choices
    listed, and the device name is validated against the tier list."""
    if not isinstance(d, dict):
        raise DeepSpeedConfigError(
            f"'zero_optimization.{block}' must be a dict "
            f"(e.g. {{\"device\": \"cpu\"}}), got {d!r}")
    unknown = sorted(set(d) - set(known))
    if unknown:
        raise DeepSpeedConfigError(
            f"Unknown 'zero_optimization.{block}' key(s) {unknown}; "
            f"valid keys: {sorted(known)}")
    device = get_scalar_param(d, "device", zc.OFFLOAD_CPU_DEVICE)
    if device not in _OFFLOAD_DEVICES:
        raise DeepSpeedConfigError(
            f"zero_optimization.{block}.device must be one of "
            f"{list(_OFFLOAD_DEVICES)} (cpu = host DRAM tier, nvme = "
            f"aio swap-file tier), got {device!r}")
    nvme_path = get_scalar_param(d, "nvme_path", None)
    if nvme_path is not None and not isinstance(nvme_path, str):
        raise DeepSpeedConfigError(
            f"zero_optimization.{block}.nvme_path must be a string "
            f"path, got {nvme_path!r}")
    return device, nvme_path


def _offload_positive_int(block, d, key, default):
    return strict_positive_int(d, key, default,
                               f"zero_optimization.{block}")


def _offload_bool(block, d, key, default=False):
    return strict_bool(d, key, default, f"zero_optimization.{block}")


@dataclass(frozen=True)
class DeepSpeedZeroOffloadParamConfig:
    device: str = zc.OFFLOAD_CPU_DEVICE
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False

    @classmethod
    def from_dict(cls, d):
        device, nvme_path = _check_offload_block(
            zc.OFFLOAD_PARAM, d,
            (zc.OFFLOAD_PARAM_DEVICE, zc.OFFLOAD_PARAM_NVME_PATH,
             zc.OFFLOAD_PARAM_BUFFER_COUNT, zc.OFFLOAD_PARAM_BUFFER_SIZE,
             zc.OFFLOAD_PARAM_MAX_IN_CPU, zc.OFFLOAD_PARAM_PIN_MEMORY))
        return cls(
            device=device,
            nvme_path=nvme_path,
            buffer_count=_offload_positive_int(
                zc.OFFLOAD_PARAM, d, zc.OFFLOAD_PARAM_BUFFER_COUNT, 5),
            buffer_size=_offload_positive_int(
                zc.OFFLOAD_PARAM, d, zc.OFFLOAD_PARAM_BUFFER_SIZE,
                100_000_000),
            max_in_cpu=_offload_positive_int(
                zc.OFFLOAD_PARAM, d, zc.OFFLOAD_PARAM_MAX_IN_CPU,
                1_000_000_000),
            pin_memory=_offload_bool(
                zc.OFFLOAD_PARAM, d, zc.OFFLOAD_PARAM_PIN_MEMORY),
        )


@dataclass(frozen=True)
class DeepSpeedZeroOffloadOptimizerConfig:
    device: str = zc.OFFLOAD_CPU_DEVICE
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write

    @classmethod
    def from_dict(cls, d):
        device, nvme_path = _check_offload_block(
            zc.OFFLOAD_OPTIMIZER, d,
            (zc.OFFLOAD_OPTIMIZER_DEVICE, zc.OFFLOAD_OPTIMIZER_NVME_PATH,
             zc.OFFLOAD_OPTIMIZER_BUFFER_COUNT,
             zc.OFFLOAD_OPTIMIZER_PIN_MEMORY,
             zc.OFFLOAD_OPTIMIZER_PIPELINE_READ,
             zc.OFFLOAD_OPTIMIZER_PIPELINE_WRITE,
             zc.OFFLOAD_OPTIMIZER_FAST_INIT))
        return cls(
            device=device,
            nvme_path=nvme_path,
            buffer_count=_offload_positive_int(
                zc.OFFLOAD_OPTIMIZER, d,
                zc.OFFLOAD_OPTIMIZER_BUFFER_COUNT, 4),
            pin_memory=_offload_bool(
                zc.OFFLOAD_OPTIMIZER, d, zc.OFFLOAD_OPTIMIZER_PIN_MEMORY),
            pipeline_read=_offload_bool(
                zc.OFFLOAD_OPTIMIZER, d,
                zc.OFFLOAD_OPTIMIZER_PIPELINE_READ),
            pipeline_write=_offload_bool(
                zc.OFFLOAD_OPTIMIZER, d,
                zc.OFFLOAD_OPTIMIZER_PIPELINE_WRITE),
            fast_init=_offload_bool(
                zc.OFFLOAD_OPTIMIZER, d, zc.OFFLOAD_OPTIMIZER_FAST_INIT),
        )


@dataclass(frozen=True)
class DeepSpeedZeroConfig:
    stage: int = zc.ZERO_OPTIMIZATION_STAGE_DEFAULT
    contiguous_gradients: bool = zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT
    reduce_scatter: bool = zc.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT
    reduce_bucket_size: int = zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT
    allgather_partitions: bool = zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT
    allgather_bucket_size: int = zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT
    overlap_comm: bool = False
    load_from_fp32_weights: bool = zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT
    elastic_checkpoint: bool = zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT
    max_live_parameters: int = zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT
    max_reuse_distance: int = zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT
    prefetch_bucket_size: int = zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT
    param_persistence_threshold: int = (
        zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT)
    gather_fp16_weights_on_model_save: bool = (
        zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)
    # explicit-dataflow collective schedule (parallel/schedule.py): the
    # "schedule" sub-block is parsed at checkpoint-block strictness
    schedule: ScheduleConfig = field(default_factory=ScheduleConfig)

    @property
    def enabled(self):
        return self.stage > zc.ZERO_OPTIMIZATION_DISABLED

    @property
    def cpu_offload(self):
        return (self.offload_optimizer is not None
                and self.offload_optimizer.device == zc.OFFLOAD_CPU_DEVICE)

    @property
    def cpu_offload_params(self):
        return (self.offload_param is not None
                and self.offload_param.device == zc.OFFLOAD_CPU_DEVICE)

    @property
    def nvme_offload(self):
        return ((self.offload_optimizer is not None
                 and self.offload_optimizer.device == zc.OFFLOAD_NVME_DEVICE)
                or (self.offload_param is not None
                    and self.offload_param.device == zc.OFFLOAD_NVME_DEVICE))

    @classmethod
    def from_dict(cls, param_dict):
        d = param_dict.get(zc.ZERO_OPTIMIZATION)
        # Legacy form: "zero_optimization": true  (== stage 1).
        if d is True:
            d = {zc.ZERO_OPTIMIZATION_STAGE: 1}
        elif d is None or d is False:
            d = {}
        elif not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"'{zc.ZERO_OPTIMIZATION}' must be a dict or bool, got {d!r}")

        stage = as_int(
            get_scalar_param(d, zc.ZERO_OPTIMIZATION_STAGE,
                             zc.ZERO_OPTIMIZATION_STAGE_DEFAULT),
            zc.ZERO_OPTIMIZATION_STAGE)
        if not 0 <= stage <= zc.MAX_STAGE_ZERO_OPTIMIZATION:
            raise DeepSpeedConfigError(
                f"ZeRO stage must be in [0, {zc.MAX_STAGE_ZERO_OPTIMIZATION}],"
                f" got {stage}")

        offload_param = None
        if d.get(zc.OFFLOAD_PARAM) is not None:
            offload_param = DeepSpeedZeroOffloadParamConfig.from_dict(
                d[zc.OFFLOAD_PARAM])
        offload_optimizer = None
        if d.get(zc.OFFLOAD_OPTIMIZER) is not None:
            offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig.from_dict(
                d[zc.OFFLOAD_OPTIMIZER])
        # Deprecated boolean spellings fold into the offload sub-configs.
        if offload_optimizer is None and d.get(
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD,
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT):
            offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(
                device=zc.OFFLOAD_CPU_DEVICE,
                pin_memory=bool(d.get(
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)))
        if offload_param is None and d.get(
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS,
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT):
            offload_param = DeepSpeedZeroOffloadParamConfig(
                device=zc.OFFLOAD_CPU_DEVICE,
                pin_memory=bool(d.get(
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)))

        overlap_default = (zc.ZERO3_OPTIMIZATION_OVERLAP_COMM_DEFAULT
                           if stage == 3 else
                           zc.ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        allgather_bucket = get_scalar_param(
            d, zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
            d.get(zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
                  zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT))

        # stage-3 scheduler knobs: bad values fail at parse (the knobs
        # are latency hints on the GSPMD path but REAL geometry for the
        # explicit schedule — a negative bucket must not reach it)
        for key in (zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                    zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
                    zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
                    zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
                    zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE,
                    zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD):
            if key in d and as_int(d[key], key) < 0:
                raise DeepSpeedConfigError(
                    f"zero_optimization.{key} must be >= 0, got "
                    f"{d[key]!r}")

        return cls(
            stage=stage,
            contiguous_gradients=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
                zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)),
            reduce_scatter=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_REDUCE_SCATTER,
                zc.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)),
            reduce_bucket_size=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT),
                zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE),
            allgather_partitions=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)),
            allgather_bucket_size=as_int(
                allgather_bucket, zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE),
            overlap_comm=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_OVERLAP_COMM, overlap_default)),
            load_from_fp32_weights=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
                zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)),
            elastic_checkpoint=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)),
            offload_param=offload_param,
            offload_optimizer=offload_optimizer,
            sub_group_size=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE,
                zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT),
                zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE),
            max_live_parameters=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
                zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT),
                zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS),
            max_reuse_distance=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE,
                zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT),
                zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE),
            prefetch_bucket_size=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
                zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT),
                zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE),
            param_persistence_threshold=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD,
                zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT),
                zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD),
            gather_fp16_weights_on_model_save=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE,
                zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)),
            schedule=_parse_schedule_block(d, stage),
        )
