"""ZeRO config objects (reference: `deepspeed/runtime/zero/config.py`,
`offload_config.py`).

Parsed into frozen dataclasses. The semantics on TPU:

- ``stage >= 1``: optimizer state carries a NamedSharding over the ``data``
  mesh axis.
- ``stage >= 2``: gradients are reduce-scattered (``psum_scatter``) instead of
  all-reduced.
- ``stage == 3``: parameters are sharded over ``data`` at rest and gathered
  per-layer by XLA (FSDP-style); prefetch/persistence knobs become latency
  hints.
"""

from dataclasses import dataclass, field
from typing import List, Optional

from ..config_utils import DeepSpeedConfigError, as_int, get_scalar_param
from . import constants as zc


@dataclass(frozen=True)
class DeepSpeedZeroOffloadParamConfig:
    device: str = zc.OFFLOAD_CPU_DEVICE
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False

    @classmethod
    def from_dict(cls, d):
        device = get_scalar_param(d, zc.OFFLOAD_PARAM_DEVICE,
                                  zc.OFFLOAD_CPU_DEVICE)
        if device not in (zc.OFFLOAD_CPU_DEVICE, zc.OFFLOAD_NVME_DEVICE):
            raise DeepSpeedConfigError(
                f"offload_param device must be cpu|nvme, got {device!r}")
        return cls(
            device=device,
            nvme_path=get_scalar_param(d, zc.OFFLOAD_PARAM_NVME_PATH, None),
            buffer_count=as_int(
                get_scalar_param(d, zc.OFFLOAD_PARAM_BUFFER_COUNT, 5),
                zc.OFFLOAD_PARAM_BUFFER_COUNT),
            buffer_size=as_int(
                get_scalar_param(d, zc.OFFLOAD_PARAM_BUFFER_SIZE, 1e8),
                zc.OFFLOAD_PARAM_BUFFER_SIZE),
            max_in_cpu=as_int(
                get_scalar_param(d, zc.OFFLOAD_PARAM_MAX_IN_CPU, 1e9),
                zc.OFFLOAD_PARAM_MAX_IN_CPU),
            pin_memory=bool(
                get_scalar_param(d, zc.OFFLOAD_PARAM_PIN_MEMORY, False)),
        )


@dataclass(frozen=True)
class DeepSpeedZeroOffloadOptimizerConfig:
    device: str = zc.OFFLOAD_CPU_DEVICE
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write

    @classmethod
    def from_dict(cls, d):
        device = get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_DEVICE,
                                  zc.OFFLOAD_CPU_DEVICE)
        if device not in (zc.OFFLOAD_CPU_DEVICE, zc.OFFLOAD_NVME_DEVICE):
            raise DeepSpeedConfigError(
                f"offload_optimizer device must be cpu|nvme, got {device!r}")
        return cls(
            device=device,
            nvme_path=get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_NVME_PATH, None),
            buffer_count=as_int(
                get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_BUFFER_COUNT, 4),
                zc.OFFLOAD_OPTIMIZER_BUFFER_COUNT),
            pin_memory=bool(
                get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_PIN_MEMORY, False)),
            pipeline_read=bool(
                get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_PIPELINE_READ, False)),
            pipeline_write=bool(
                get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_PIPELINE_WRITE,
                                 False)),
            fast_init=bool(
                get_scalar_param(d, zc.OFFLOAD_OPTIMIZER_FAST_INIT, False)),
        )


@dataclass(frozen=True)
class DeepSpeedZeroConfig:
    stage: int = zc.ZERO_OPTIMIZATION_STAGE_DEFAULT
    contiguous_gradients: bool = zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT
    reduce_scatter: bool = zc.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT
    reduce_bucket_size: int = zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT
    allgather_partitions: bool = zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT
    allgather_bucket_size: int = zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT
    overlap_comm: bool = False
    load_from_fp32_weights: bool = zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT
    elastic_checkpoint: bool = zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT
    max_live_parameters: int = zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT
    max_reuse_distance: int = zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT
    prefetch_bucket_size: int = zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT
    param_persistence_threshold: int = (
        zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT)
    gather_fp16_weights_on_model_save: bool = (
        zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)

    @property
    def enabled(self):
        return self.stage > zc.ZERO_OPTIMIZATION_DISABLED

    @property
    def cpu_offload(self):
        return (self.offload_optimizer is not None
                and self.offload_optimizer.device == zc.OFFLOAD_CPU_DEVICE)

    @property
    def cpu_offload_params(self):
        return (self.offload_param is not None
                and self.offload_param.device == zc.OFFLOAD_CPU_DEVICE)

    @property
    def nvme_offload(self):
        return ((self.offload_optimizer is not None
                 and self.offload_optimizer.device == zc.OFFLOAD_NVME_DEVICE)
                or (self.offload_param is not None
                    and self.offload_param.device == zc.OFFLOAD_NVME_DEVICE))

    @classmethod
    def from_dict(cls, param_dict):
        d = param_dict.get(zc.ZERO_OPTIMIZATION)
        # Legacy form: "zero_optimization": true  (== stage 1).
        if d is True:
            d = {zc.ZERO_OPTIMIZATION_STAGE: 1}
        elif d is None or d is False:
            d = {}
        elif not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"'{zc.ZERO_OPTIMIZATION}' must be a dict or bool, got {d!r}")

        stage = as_int(
            get_scalar_param(d, zc.ZERO_OPTIMIZATION_STAGE,
                             zc.ZERO_OPTIMIZATION_STAGE_DEFAULT),
            zc.ZERO_OPTIMIZATION_STAGE)
        if not 0 <= stage <= zc.MAX_STAGE_ZERO_OPTIMIZATION:
            raise DeepSpeedConfigError(
                f"ZeRO stage must be in [0, {zc.MAX_STAGE_ZERO_OPTIMIZATION}],"
                f" got {stage}")

        offload_param = None
        if d.get(zc.OFFLOAD_PARAM) is not None:
            offload_param = DeepSpeedZeroOffloadParamConfig.from_dict(
                d[zc.OFFLOAD_PARAM])
        offload_optimizer = None
        if d.get(zc.OFFLOAD_OPTIMIZER) is not None:
            offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig.from_dict(
                d[zc.OFFLOAD_OPTIMIZER])
        # Deprecated boolean spellings fold into the offload sub-configs.
        if offload_optimizer is None and d.get(
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD,
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT):
            offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(
                device=zc.OFFLOAD_CPU_DEVICE,
                pin_memory=bool(d.get(
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)))
        if offload_param is None and d.get(
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS,
                zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT):
            offload_param = DeepSpeedZeroOffloadParamConfig(
                device=zc.OFFLOAD_CPU_DEVICE,
                pin_memory=bool(d.get(
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
                    zc.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)))

        overlap_default = (zc.ZERO3_OPTIMIZATION_OVERLAP_COMM_DEFAULT
                           if stage == 3 else
                           zc.ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        allgather_bucket = get_scalar_param(
            d, zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
            d.get(zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
                  zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT))

        return cls(
            stage=stage,
            contiguous_gradients=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
                zc.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)),
            reduce_scatter=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_REDUCE_SCATTER,
                zc.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)),
            reduce_bucket_size=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT),
                zc.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE),
            allgather_partitions=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                zc.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)),
            allgather_bucket_size=as_int(
                allgather_bucket, zc.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE),
            overlap_comm=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_OVERLAP_COMM, overlap_default)),
            load_from_fp32_weights=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
                zc.ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)),
            elastic_checkpoint=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                zc.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)),
            offload_param=offload_param,
            offload_optimizer=offload_optimizer,
            sub_group_size=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE,
                zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT),
                zc.ZERO_OPTIMIZATION_SUB_GROUP_SIZE),
            max_live_parameters=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
                zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT),
                zc.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS),
            max_reuse_distance=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE,
                zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT),
                zc.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE),
            prefetch_bucket_size=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
                zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT),
                zc.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE),
            param_persistence_threshold=as_int(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD,
                zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT),
                zc.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD),
            gather_fp16_weights_on_model_save=bool(get_scalar_param(
                d, zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE,
                zc.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)),
        )
