"""Batch-size warmup scheduler (fork addition; reference:
`deepspeed/runtime/bs_schedules.py:5-69`).

Linearly increases the micro batch size from
``ceil(min_batch_size_multiplier * final_batch_size)`` to
``final_batch_size`` over ``warmup_num_steps`` in ``num_intervals`` jumps.
Note: a changing batch size is a *shape* change; the engine keeps one
compiled train step per distinct batch size (XLA caches by shape), so
``num_intervals`` bounds the number of compilations.
"""

import math


class BatchSizeScheduler:
    """Step-indexed piecewise-constant batch-size schedule."""

    def __init__(self, final_batch_size, min_batch_size_multiplier=0.01,
                 warmup_num_steps=1000, num_intervals=4,
                 last_batch_iteration=-1, deepspeed=None):
        self.final_batch_size = final_batch_size
        self.min_batch_size_multiplier = min_batch_size_multiplier
        self.warmup_num_steps = warmup_num_steps
        self.num_intervals = num_intervals
        self.last_batch_iteration = last_batch_iteration
        self.deepspeed = deepspeed
        self.schedule = self._build_schedule()
        self.current_batch_size = None

    def _build_schedule(self):
        start = math.ceil(self.min_batch_size_multiplier *
                          self.final_batch_size)
        schedule = {}
        prev_bs = None
        for i in range(self.num_intervals):
            frac = i / max(1, self.num_intervals - 1)
            step = int(round(frac * self.warmup_num_steps))
            bs = int(round(start + frac * (self.final_batch_size - start)))
            if bs != prev_bs:
                schedule[step] = bs
            prev_bs = bs
        return schedule

    def get_current_batch_size(self):
        boundaries = sorted(self.schedule.keys(), reverse=True)
        for step in boundaries:
            if self.last_batch_iteration >= step:
                return self.schedule[step]
        return self.schedule[boundaries[-1]]

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self.current_batch_size = self.get_current_batch_size()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
