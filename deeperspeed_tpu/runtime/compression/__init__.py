from .cupy import CupyBackend

__all__ = ["CupyBackend"]
