"""Sign-bit packing backend (reference: `deepspeed/runtime/compression/
cupy.py:10` — `CupyBackend.compress_by_chunk` et al.).

The reference packs sign bits on the GPU with cupy so the 1-bit
collectives move 1/32 of the fp32 volume. Here packing runs on the host
with numpy (the in-mesh compressed collectives on TPU move int8 signs —
the fabric makes bit-level packing a non-goal), but the class name and
method surface are preserved so reference-facing code imports unchanged.
"""

import numpy as np


class CupyBackend:
    """numpy-backed bit packing with the reference's method names."""

    def torch2cupy(self, tensor):
        return np.asarray(tensor)

    def cupy2torch(self, cupy_tensor):
        return np.asarray(cupy_tensor)

    def compress_by_chunk(self, dense_array, num_chunks):
        """Pack the sign bits of `dense_array` in `num_chunks` chunks
        (reference `cupy.py:24`): the *elements* are chunked first, then
        each chunk is packed independently, so a server rank can
        decompress its own chunk without the others."""
        arr = np.asarray(dense_array)
        signs = (arr.reshape(-1) >= 0)
        return [np.ascontiguousarray(np.packbits(c))
                for c in np.array_split(signs, num_chunks)]

    def decompress(self, packed_chunks, numel, dtype=np.float32):
        """Inverse of `compress_by_chunk` for a chunk list covering
        `numel` total elements: ±1 array of length `numel`. Each chunk is
        unpacked independently (chunks are byte-padded separately)."""
        counts = [len(c) for c in
                  np.array_split(np.empty(numel, np.bool_),
                                 len(packed_chunks))]
        outs = []
        for packed, n in zip(packed_chunks, counts):
            bits = np.unpackbits(np.asarray(packed, np.uint8))[:n]
            outs.append(bits.astype(dtype) * 2 - 1)
        return np.concatenate(outs) if outs else np.zeros(0, dtype)
