"""Unified telemetry: step/phase tracing, goodput + MFU accounting, and
trigger-driven profiler capture.

The reference instruments training piecemeal (`wall_clock_breakdown`
CUDA timers, a standalone flops profiler, tensorboard scalars); here the
pieces fuse into one config-driven layer the engine consults every step:

- **Span tracer** (`telemetry.span("data_fetch")`): host-side phase
  timing around every boundary the engine already owns — data fetch,
  host→device batch upload, train-step dispatch, checkpoint snapshot
  stall, sentinel escalation, rollback restore. Each span also enters a
  `jax.profiler.TraceAnnotation`, so a device trace captured over the
  same steps carries the same phase names, and the host spans export as
  Chrome-trace/Perfetto JSON per capture window.
- **Goodput accounting**: cumulative wall time inside step windows is
  classified into productive / data_wait / ckpt_stall / quarantined /
  rollback buckets, emitted as `Train/Goodput/*` scalars plus a running
  `Train/Goodput/fraction` (productive over everything).
- **In-engine MFU**: the engine AOT-compiles its train step when MFU is
  on, the per-variant flops are harvested ONCE from
  `compiled.cost_analysis()` (`profiling.flops_profiler._cost_analysis`)
  and every step emits `Train/Samples/mfu` and achieved-FLOPS/s against
  the per-device-kind peak table (`profiling.hardware`).
- **Trigger-driven capture**: the validated ``"telemetry"`` JSON block
  arms programmatic `jax.profiler` trace windows
  (``capture: {start_step, num_steps}``), periodic HBM
  `memory_stats` watermark scalars, and an on-anomaly hook — the
  sentinel's warn/quarantine/rollback path and the hang watchdog grab a
  memory snapshot immediately and a trace of the following step(s)
  automatically, at most once per anomaly episode.

Zero-overhead path: when the block is absent the engine holds
`NULL_TELEMETRY`, whose hooks are empty methods and whose `span()`
returns a shared no-op context manager — the compiled programs and the
host loop are unchanged.
"""

import json
import os
import threading
import time
import weakref

from ..utils.logging import log_dist, logger

# one process-wide flag: jax.profiler supports a single active trace;
# overlapping windows (scheduled + anomaly) must coalesce, not crash
_TRACE_LOCK = threading.Lock()
_TRACE_ACTIVE = False


def _release_orphaned_trace(wstate):
    """weakref.finalize target: a Telemetry collected mid-capture-window
    must stop the jax trace it started and release the process-wide
    flag, or every later window in the process silently skips tracing
    (and the profiler keeps buffering forever). Shares only the mutable
    `wstate` dict with the owner — no reference cycle."""
    global _TRACE_ACTIVE
    if not wstate.get("started_jax"):
        return
    with _TRACE_LOCK:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 - interpreter may be tearing down
            pass
        _TRACE_ACTIVE = False
        wstate["started_jax"] = False


def _cost_analysis_flops(compiled):
    """Per-device program flops from an AOT-compiled executable (None
    when the backend reports no cost model)."""
    from ..profiling.flops_profiler.profiler import _cost_analysis
    flops = float(_cost_analysis(compiled).get("flops", 0.0))
    return flops if flops > 0 else None


class _AOTStep:
    """AOT executable with a one-time jit fallback.

    The executable is compiled against the FIRST call's input shardings
    and layouts. GSPMD may settle the donated state onto different
    output shardings (the jit path silently retraces once for exactly
    this; `_build_train_window`'s docstring records the same effect for
    layouts) — and a checkpoint restore re-places state the same way.
    The AOT call then raises a sharding/layout mismatch BEFORE executing
    (inputs intact), so we degrade to the plain jit wrapper, which
    re-specializes per input just like the telemetry-off path. Total
    compile count matches the jit path's own worst case (two)."""

    def __init__(self, compiled, rebuild):
        self._fn = compiled
        self._rebuild = rebuild
        self._fell_back = False

    def __call__(self, *args):
        if not self._fell_back:
            try:
                return self._fn(*args)
            # ValueError: sharding/layout mismatch; TypeError: aval
            # mismatch ("Argument types differ...") — both raised by the
            # Compiled input checks BEFORE execution, so inputs (incl.
            # donated buffers) are intact and the jit retry is safe.
            # Anything raised mid-execution propagates.
            except (ValueError, TypeError) as e:
                logger.warning(
                    "telemetry: inputs settled away from the first-call "
                    f"AOT compile ({e}); this step variant "
                    "re-specializes under jit from here on")
                self._fell_back = True
                self._fn = self._rebuild()
        return self._fn(*args)


def aot_compile_with_flops(jitted, args, rebuild=None):
    """Lower+compile `jitted` against concrete `args` (one trace, one
    compile — the AOT executable IS the step the engine runs, so
    `cost_analysis` costs nothing extra). Returns (callable, flops);
    falls back to the plain jit wrapper on any failure. `rebuild`
    (() -> fresh jit wrapper) arms the one-time sharding-settle fallback
    — see `_AOTStep`."""
    try:
        compiled = jitted.lower(*args).compile()
        flops = _cost_analysis_flops(compiled)
    except Exception as e:  # noqa: BLE001 - telemetry must not kill training
        logger.warning(f"telemetry: AOT flops harvest failed "
                       f"({type(e).__name__}: {e}); MFU scalars disabled "
                       f"for this step variant")
        return jitted, None
    if rebuild is not None:
        return _AOTStep(compiled, rebuild), flops
    return compiled, flops


class _NullSpan:
    """Shared no-op context manager (the zero-overhead span)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: phase accumulation + optional capture-buffer entry
    + a mirrored `jax.profiler.TraceAnnotation` so device timelines show
    the same names."""
    __slots__ = ("tel", "name", "t0", "ann")

    def __init__(self, tel, name):
        self.tel = tel
        self.name = name
        self.ann = None

    def __enter__(self):
        tel = self.tel
        self.t0 = time.perf_counter()
        tel._depth += 1
        if tel.mirror_annotations:
            import jax
            self.ann = jax.profiler.TraceAnnotation(self.name)
            self.ann.__enter__()
        return self

    def __exit__(self, *exc):
        tel = self.tel
        t1 = time.perf_counter()
        tel._depth -= 1
        if self.ann is not None:
            self.ann.__exit__(*exc)
        tel._on_span(self.name, self.t0, t1 - self.t0, tel._depth)
        return False


class SpanTracer:
    """Host-side span recorder. Always accumulates per-step phase
    durations (goodput inputs); buffers full (name, ts, dur, depth)
    events only while a capture window is open, and exports them as a
    Chrome-trace JSON (`{"traceEvents": [...]}`, "X" complete events,
    microsecond timestamps) loadable in Perfetto/chrome://tracing."""

    def __init__(self, mirror_annotations=True):
        self.mirror_annotations = mirror_annotations
        self._depth = 0
        self._phase_acc = {}        # name -> seconds, this step window
        self._buffer = []           # capture-window events
        self.capturing = False

    def span(self, name):
        return _Span(self, name)

    def _on_span(self, name, t0, dur, depth):
        self._phase_acc[name] = self._phase_acc.get(name, 0.0) + dur
        if self.capturing:
            self._buffer.append((name, t0, dur, depth))

    def record_event(self, name, t0, dur, depth=0):
        """Append one pre-timed event to an open capture window (the
        serving engine's per-request lifecycle records ride this — they
        are not live spans, the request's wall time was measured by the
        scheduler). No-op outside a window."""
        if self.capturing:
            self._buffer.append((str(name), float(t0), float(dur),
                                 int(depth)))

    def drain_phases(self):
        phases, self._phase_acc = self._phase_acc, {}
        return phases

    def start_capture(self):
        self._buffer = []
        self.capturing = True

    def stop_capture(self):
        self.capturing = False
        events, self._buffer = self._buffer, []
        return events

    @staticmethod
    def chrome_trace(events, pid=0, metadata=None):
        """Chrome-trace dict for a list of (name, t0, dur, depth);
        `metadata` (kernel dispatch report, env fingerprint) lands in
        the trace's ``otherData``."""
        trace_events = [
            {"name": name, "ph": "X", "pid": pid, "tid": depth,
             "ts": t0 * 1e6, "dur": dur * 1e6,
             "cat": "deeperspeed_tpu"}
            for name, t0, dur, depth in events]
        trace = {"traceEvents": trace_events,
                 "displayTimeUnit": "ms"}
        if metadata:
            trace["otherData"] = metadata
        return trace

    @classmethod
    def export_chrome_trace(cls, events, path, pid=0, metadata=None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(cls.chrome_trace(events, pid=pid,
                                       metadata=metadata), f)
        return path


# goodput bucket names, in emission order
GOODPUT_BUCKETS = ("productive", "data_wait", "param_wait", "ckpt_stall",
                   "quarantined", "rollback")


class GoodputMeter:
    """Cumulative wall-time classifier over step windows.

    Every `account()` call covers one step window of `dt` seconds and
    splits it: data-fetch span time is always charged to `data_wait`;
    host-visible parameter-fetch stalls (`param_gather` spans — the
    offload tiers waiting on a segment upload; the in-jit explicit
    ZeRO-3 gathers are scheduled/overlapped inside the program and show
    up in device traces, not here) to `param_wait`; checkpoint snapshot
    stall (the delta of the async manager's cumulative stall inside
    this window) to `ckpt_stall`; the rest goes to `productive` for
    taken steps, `quarantined` for in-jit skipped updates (sentinel
    quarantine or fp16 overflow — either way the step burned time
    without advancing), and `rollback` for windows that ended in a
    checkpoint restore."""

    def __init__(self):
        self.buckets = {name: 0.0 for name in GOODPUT_BUCKETS}

    def account(self, dt, verdict, data_wait=0.0, param_wait=0.0,
                ckpt_stall=0.0):
        data_wait = min(max(data_wait, 0.0), dt)
        param_wait = min(max(param_wait, 0.0), dt - data_wait)
        ckpt_stall = min(max(ckpt_stall, 0.0),
                         dt - data_wait - param_wait)
        rest = dt - data_wait - param_wait - ckpt_stall
        self.buckets["data_wait"] += data_wait
        self.buckets["param_wait"] += param_wait
        self.buckets["ckpt_stall"] += ckpt_stall
        if verdict == "rollback":
            self.buckets["rollback"] += rest
        elif verdict in ("quarantined", "overflow"):
            self.buckets["quarantined"] += rest
        else:
            self.buckets["productive"] += rest

    @property
    def total(self):
        return sum(self.buckets.values())

    @property
    def fraction(self):
        total = self.total
        return self.buckets["productive"] / total if total > 0 else 1.0

    def scalars(self):
        out = {f"Train/Goodput/{name}_s": secs
               for name, secs in self.buckets.items()}
        out["Train/Goodput/fraction"] = self.fraction
        return out


class _NullTelemetry:
    """The absent-config telemetry object: every hook is a no-op and
    `span()` hands back one shared do-nothing context manager."""

    enabled = False
    wants_flops = False
    spans_enabled = False
    fleet = None

    def span(self, name):  # noqa: ARG002
        return _NULL_SPAN

    def step_annotation(self, step):  # noqa: ARG002
        return _NULL_SPAN

    def on_step_start(self, step):  # noqa: ARG002
        pass

    def on_step_end(self, engine, verdict="ok", flops=None, steps=1,
                    tokens=None, offload=None):
        pass

    def on_anomaly(self, engine, kind, step=None):
        pass

    def register_compiled(self, key, flops):
        pass

    def close(self):
        pass


NULL_TELEMETRY = _NullTelemetry()


class Telemetry:
    """Config-driven engine telemetry (the ``"telemetry"`` JSON block).

    Constructed by the engine AFTER the monitor; emits scalars through
    `monitor.record` keyed by the engine's global sample count, so
    goodput/MFU/memory series line up with the loss series."""

    enabled = True

    def __init__(self, monitor=None, devices=None, goodput=True, mfu=True,
                 spans=True, trace_dir=None, capture=None,
                 memory_watermark_interval_steps=0,
                 capture_on_anomaly=False, anomaly_capture_steps=1,
                 fleet=None):
        self.monitor = monitor
        self.devices = list(devices or [])
        self.goodput_enabled = bool(goodput)
        self.mfu_enabled = bool(mfu)
        self.spans_enabled = bool(spans)
        self.trace_dir = trace_dir
        self.capture_start_step = None
        self.capture_num_steps = 0
        if capture:
            self.capture_start_step = int(capture["start_step"])
            self.capture_num_steps = int(capture["num_steps"])
        self.memory_watermark_interval = int(memory_watermark_interval_steps)
        self.capture_on_anomaly = bool(capture_on_anomaly)
        self.anomaly_capture_steps = int(anomaly_capture_steps)

        self.tracer = SpanTracer(mirror_annotations=self.spans_enabled)
        self.goodput = GoodputMeter()
        self.compiled_flops = {}    # step-variant key -> per-device flops

        # fleet observability (runtime/fleet.py; the telemetry.fleet
        # sub-block): cross-host scalar aggregation, merged Perfetto
        # capture, collective-skew straggler probe. None when absent —
        # the per-host path is unchanged.
        from .fleet import build_fleet
        self.fleet = build_fleet(fleet)

        self._step_t0 = None
        self._steps_seen = 0
        self._last_ckpt_stall = None
        self._peak_flops = None
        # packed-batch effective-token accounting (runtime/packing.py):
        # cumulative (non-pad, non-cross-document) vs possible targets
        self._tokens_effective = 0
        self._tokens_total = 0

        # capture-window state. `started_jax` lives in a dict shared
        # with a weakref.finalize below: a Telemetry collected mid-window
        # (bench ladders delete failed engines and retry) must still stop
        # the jax trace and release the process-wide flag — the atexit
        # hook alone no-ops once the object is gone.
        self._window_open = False
        self._window_tag = None
        self._window_steps_left = 0
        self._wstate = {"started_jax": False}
        self._finalizer = weakref.finalize(self, _release_orphaned_trace,
                                           self._wstate)
        self._scheduled_done = False
        self._armed = []            # pending (tag, num_steps) requests

        # anomaly episode state
        self._anomaly_episode = False
        self.anomaly_captures = 0
        self.exported_traces = []   # chrome-trace JSON paths written

        # flush an open capture window at interpreter exit: a run that
        # ends (or dies) mid-window must still stop the jax trace and
        # export the collected spans — and release the process-wide
        # active-trace flag for any later engine. Weakly held, like the
        # monitor's and checkpoint manager's hooks.
        from .utils import register_weak_atexit
        self._atexit = register_weak_atexit(self, "close")

    # ------------------------------------------------------------------
    # spans
    # ------------------------------------------------------------------

    def span(self, name):
        # goodput keeps phase timing alive even with spans off: the
        # data_wait / ckpt-stall buckets are fed by these spans, and
        # `spans: false` must not silently blind the meter. What
        # spans: false DOES turn off: the jax.profiler annotation
        # mirroring (tracer.mirror_annotations) and span capture/export
        # (_open_window skips start_capture).
        if not (self.spans_enabled or self.goodput_enabled
                or self.fleet is not None):
            return _NULL_SPAN
        return self.tracer.span(name)

    def step_annotation(self, step):
        """`jax.profiler.StepTraceAnnotation` around the train-step
        dispatch: device timelines group kernels by train step."""
        if not self.spans_enabled:
            return _NULL_SPAN
        import jax
        return jax.profiler.StepTraceAnnotation("train",
                                                step_num=int(step))

    # ------------------------------------------------------------------
    # MFU
    # ------------------------------------------------------------------

    @property
    def wants_flops(self):
        return self.mfu_enabled

    def register_compiled(self, key, flops):
        """Record a step variant's per-device program flops (harvested
        once from `compiled.cost_analysis()` at compile time)."""
        if flops:
            self.compiled_flops[key] = float(flops)
            log_dist(f"telemetry: step variant {key} costs "
                     f"{flops / 1e9:.2f} GFLOPs/device per call",
                     ranks=[0])

    def _peak(self):
        if self._peak_flops is None:
            from ..profiling.hardware import peak_flops_per_chip
            dev = self.devices[0] if self.devices else None
            self._peak_flops = peak_flops_per_chip(dev)
        return self._peak_flops

    # ------------------------------------------------------------------
    # step hooks
    # ------------------------------------------------------------------

    def on_step_start(self, step):
        self._step_t0 = time.perf_counter()
        # scheduled window: arm once when the step counter reaches it
        if (self.capture_start_step is not None
                and not self._scheduled_done
                and step >= self.capture_start_step):
            self._scheduled_done = True
            self._armed.append((f"step{step}", self.capture_num_steps))
        if self._armed and not self._window_open:
            tag, n_steps = self._armed.pop(0)
            self._open_window(tag, n_steps)

    def on_step_end(self, engine, verdict="ok", flops=None, steps=1,
                    tokens=None, offload=None):
        """Close one step window: goodput accounting, MFU/memory
        scalars, capture-window bookkeeping. `steps` > 1 for fused
        `train_steps` windows (one call covers n optimizer steps).

        `offload` = the tiered-offload runner's per-step counters
        ({prefetch_stall_s, bytes_h2d, bytes_d2h, ...}): emitted as
        `Train/Offload/*` scalars so the streaming tier's wire traffic
        and residual prefetch stalls sit next to the goodput series
        (the stall seconds are ALSO in the param_wait bucket via the
        param_gather span — this scalar is the per-step ms view).

        `tokens` = (effective, total) target counts for packed ragged
        batches (`runtime.packing.packed_batch_token_stats`): raw
        throughput/MFU count pad tokens and cross-document positions as
        productive work, so packing wins would be invisible — these
        emit effective-tokens/s and effective-MFU next to the raw
        scalars, plus the running effective-token fraction."""
        t1 = time.perf_counter()
        dt = (t1 - self._step_t0) if self._step_t0 is not None else 0.0
        self._step_t0 = None
        self._steps_seen += steps
        phases = self.tracer.drain_phases()

        scalars = {}
        data_wait = phases.get("data_fetch", 0.0)
        param_wait = phases.get("param_gather", 0.0)
        ckpt_delta = 0.0
        if self.goodput_enabled or self.fleet is not None:
            # checkpoint stall is shared by the goodput meter and the
            # fleet window summaries: read it once per step
            manager = getattr(engine, "checkpoint_manager", None)
            stall = getattr(manager, "total_stall_s", 0.0)
            if self._last_ckpt_stall is None:
                self._last_ckpt_stall = stall
            ckpt_delta = max(stall - self._last_ckpt_stall, 0.0)
            self._last_ckpt_stall = stall
        if self.goodput_enabled:
            self.goodput.account(dt, verdict,
                                 data_wait=data_wait,
                                 param_wait=param_wait,
                                 ckpt_stall=ckpt_delta)
            scalars.update(self.goodput.scalars())
        if self.fleet is not None:
            scalars.update(self.fleet.on_step_end(
                dt, data_wait_s=data_wait, ckpt_stall_s=ckpt_delta,
                steps=steps))

        if self.mfu_enabled and flops and dt > 0:
            achieved = flops / dt          # per-device FLOPS/s
            scalars["Train/Samples/achieved_tflops"] = achieved / 1e12
            scalars["Train/Samples/mfu"] = achieved / self._peak()

        if tokens is not None and dt > 0:
            eff, total = tokens
            self._tokens_effective += int(eff)
            self._tokens_total += int(total)
            scalars["Train/Samples/tokens_per_sec"] = total / dt
            scalars["Train/Samples/effective_tokens_per_sec"] = eff / dt
            if self._tokens_total:
                scalars["Train/Goodput/effective_token_fraction"] = (
                    self._tokens_effective / self._tokens_total)
            if self.mfu_enabled and flops and total:
                # MFU counting only loss-bearing tokens as productive:
                # the raw scalar times flops the kernels BURNED; this
                # one credits only the fraction the loss consumed
                scalars["Train/Samples/effective_mfu"] = (
                    flops / dt / self._peak()) * (eff / total)

        if offload is not None:
            scalars["Train/Offload/prefetch_stall_ms"] = \
                offload.get("prefetch_stall_s", 0.0) * 1e3
            scalars["Train/Offload/bytes_h2d"] = \
                offload.get("bytes_h2d", 0)
            scalars["Train/Offload/bytes_d2h"] = \
                offload.get("bytes_d2h", 0)

        if (self.memory_watermark_interval > 0
                and self._steps_seen % self.memory_watermark_interval < steps):
            scalars.update(self._memory_scalars())

        if scalars and self.monitor is not None:
            self.monitor.record(getattr(engine, "global_samples", 0),
                                scalars)

        if verdict == "ok":
            self._anomaly_episode = False

        if self._window_open:
            self._window_steps_left -= steps
            if self._window_steps_left <= 0:
                self._close_window()

    # ------------------------------------------------------------------
    # anomaly hook (sentinel escalation path + hang watchdog)
    # ------------------------------------------------------------------

    def on_anomaly(self, engine, kind, step=None):
        """Called by the sentinel when a step is flagged (and by the
        hang watchdog on expiry): snapshot device memory NOW and arm a
        trace window over the next step(s). Fires at most once per
        anomaly episode — a run of consecutive anomalous steps produces
        one capture, and the episode re-arms after the next healthy
        step."""
        if not self.capture_on_anomaly or self._anomaly_episode:
            return
        self._anomaly_episode = True
        self.anomaly_captures += 1
        step = step if step is not None else \
            getattr(engine, "global_steps", 0)
        tag = f"anomaly_{kind}_step{step}"
        self.write_memory_snapshot(tag)
        # trace the FOLLOWING step(s): the flagged step already ran
        self._armed.append((tag, self.anomaly_capture_steps))
        log_dist(f"telemetry: anomaly ({kind}) at step {step} — memory "
                 f"snapshot written, trace armed for the next "
                 f"{self.anomaly_capture_steps} step(s)", ranks=[0])

    # ------------------------------------------------------------------
    # capture windows
    # ------------------------------------------------------------------

    def _open_window(self, tag, n_steps):
        global _TRACE_ACTIVE
        self._window_open = True
        self._window_tag = tag
        self._window_steps_left = max(int(n_steps), 1)
        if self.spans_enabled:
            # spans: false turns span capture/export off entirely — the
            # window still drives the jax profiler trace below
            self.tracer.start_capture()
        self._wstate["started_jax"] = False
        if self.trace_dir:
            with _TRACE_LOCK:
                if not _TRACE_ACTIVE:
                    try:
                        import jax
                        jax.profiler.start_trace(self.trace_dir)
                        _TRACE_ACTIVE = True
                        self._wstate["started_jax"] = True
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            f"telemetry: jax profiler trace failed to "
                            f"start ({e}); host spans still captured")

    def _close_window(self):
        global _TRACE_ACTIVE
        events = self.tracer.stop_capture()
        tag = self._window_tag
        self._window_open = False
        self._window_tag = None
        if self._wstate["started_jax"]:
            with _TRACE_LOCK:
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    logger.warning(f"telemetry: stop_trace failed ({e})")
                _TRACE_ACTIVE = False
                self._wstate["started_jax"] = False
        if self.trace_dir and events:
            try:
                import jax
                pid = jax.process_index()
            except Exception:  # noqa: BLE001
                pid = 0
            path = os.path.join(self.trace_dir, f"spans_{tag}.json")
            # the capture artifact carries the kernel dispatch report:
            # WHICH flash/decode geometry produced these timings is as
            # load-bearing as the timings themselves
            from .fleet import _safe_dispatch_report
            self.exported_traces.append(
                SpanTracer.export_chrome_trace(
                    events, path, pid=pid,
                    metadata={"host": pid,
                              "dispatch": _safe_dispatch_report()}))
            log_dist(f"telemetry: capture window '{tag}' closed — "
                     f"{len(events)} host spans -> {path}", ranks=[0])
        if self.fleet is not None and self.trace_dir:
            # cross-host merge: every host ships its (bounded) events;
            # rank 0 collects one lane per host into a single Perfetto
            # trace next to the per-host exports
            self.fleet.ship_capture(tag, events)
            merged = self.fleet.merged_trace(tag, self.trace_dir)
            if merged:
                self.exported_traces.append(merged)

    # ------------------------------------------------------------------
    # memory
    # ------------------------------------------------------------------

    def _memory_scalars(self):
        """HBM watermark scalars from the first local device (watermarks
        are per-chip and SPMD keeps chips symmetric)."""
        stats = self._device_memory_stats()
        first = next(iter(stats.values()), None) or {}
        out = {}
        if "bytes_in_use" in first:
            out["Train/Memory/hbm_bytes_in_use"] = first["bytes_in_use"]
        if "peak_bytes_in_use" in first:
            out["Train/Memory/hbm_peak_bytes"] = \
                first["peak_bytes_in_use"]
        return out

    def _device_memory_stats(self):
        out = {}
        for dev in self.devices:
            try:
                out[str(dev)] = dev.memory_stats() or {}
            except Exception:  # noqa: BLE001 - backends without stats
                out[str(dev)] = {}
        return out

    def write_memory_snapshot(self, tag):
        """Per-device `memory_stats` JSON under the trace dir (the
        anomaly hook's 'what was HBM doing' artifact). Thread-safe: the
        hang watchdog calls this from its own thread."""
        if not self.trace_dir:
            return None
        path = os.path.join(self.trace_dir, f"memory_{tag}.json")
        os.makedirs(self.trace_dir, exist_ok=True)
        # true epoch timestamp: snapshot files are correlated with logs
        # and other hosts' artifacts offline
        payload = {"tag": tag, "time": time.time(),  # dslint: disable=wall-clock
                   "devices": self._device_memory_stats()}
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)
        return path

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def close(self):
        """Flush an open capture window (export what was collected) and
        detach the atexit hook. Idempotent."""
        if self._window_open:
            self._close_window()
        try:
            import atexit
            atexit.unregister(self._atexit)
        except Exception:  # pragma: no cover
            pass


def build_telemetry(config_dict, monitor=None, devices=None):
    """Telemetry (or NULL_TELEMETRY) from a parsed telemetry config
    dict (`DeepSpeedConfig.telemetry_config`)."""
    if not config_dict or not config_dict.get("enabled"):
        return NULL_TELEMETRY
    kwargs = {k: v for k, v in config_dict.items() if k != "enabled"}
    return Telemetry(monitor=monitor, devices=devices, **kwargs)
