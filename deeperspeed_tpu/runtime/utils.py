"""Runtime utilities (reference: `deepspeed/runtime/utils.py`).

Includes the balanced-partition solver used by the pipeline module
(`partition_balanced`, reference `utils.py:399`), the `PartitionedTensor`
scatter/gather container used for activation ("slice") parallelism
(reference `utils.py:417-525`), gradient-norm helpers, and the fork's
`GradientNoiseScale` estimator (reference `utils.py:618-674`).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def noop_decorator(func):
    return func


def register_weak_atexit(obj, method_name):
    """Register `obj.<method_name>()` to run at interpreter exit, held
    through a weakref: the atexit registry must not pin `obj` (engines,
    monitors, checkpoint managers are constructed per test/per run) for
    the process lifetime. Returns the registered hook for
    `atexit.unregister`."""
    import atexit
    import weakref

    obj_ref = weakref.ref(obj)

    def hook():  # pragma: no cover - interpreter teardown
        target = obj_ref()
        if target is not None:
            getattr(target, method_name)()

    atexit.register(hook)
    return hook


def call_to_str(base, *args, **kwargs):
    """Construct a string representation of a call, e.g. ``f(1, b=2)``."""
    name = f"{base}("
    name += ", ".join(repr(arg) for arg in args)
    if args and kwargs:
        name += ", "
    name += ", ".join(f"{key}={repr(val)}" for key, val in kwargs.items())
    name += ")"
    return name


# ---------------------------------------------------------------------------
# Norm helpers (jit-friendly; axis_name psums replace mpu allreduces)
# ---------------------------------------------------------------------------

def global_norm(tree, axis_name=None):
    """L2 norm over a pytree; if `axis_name` is given (inside shard_map),
    sums squares across that mesh axis first (model-parallel-aware norm,
    reference `utils.py:300-306`)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(leaf.astype(jnp.float32)))
             for leaf in leaves)
    if axis_name is not None:
        sq = jax.lax.psum(sq, axis_name=axis_name)
    return jnp.sqrt(sq)


def clip_grad_norm_(grads, max_norm, axis_name=None, norm=None):
    """Scale the grad pytree so its global L2 norm is at most `max_norm`.
    Overflowed (non-finite) norms leave grads unscaled — the loss-scaler
    skip path handles them. Returns (clipped_grads, total_norm)."""
    total_norm = global_norm(grads, axis_name) if norm is None else norm
    clip_coef = max_norm / (total_norm + 1e-6)
    clip_coef = jnp.where(jnp.isfinite(total_norm),
                          jnp.minimum(clip_coef, 1.0), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * clip_coef).astype(g.dtype), grads)
    return clipped, total_norm


def get_grad_norm(grads, mpu=None, norm_type=2):
    """Host-side grad norm; -1 signals inf/nan (reference contract)."""
    if norm_type != 2:
        leaves = jax.tree_util.tree_leaves(grads)
        total = max(float(jnp.max(jnp.abs(l))) for l in leaves)
    else:
        total = float(global_norm(grads))
    if not np.isfinite(total):
        return -1
    return total


def get_weight_norm(weights, mpu=None, norm_type=2):
    return get_grad_norm(weights, mpu=mpu, norm_type=norm_type)


# ---------------------------------------------------------------------------
# Balanced partitioning (pipeline layer assignment)
# ---------------------------------------------------------------------------

def prefix_sum_inc(weights):
    """Inclusive prefix sum: [3,4,5] -> [3,7,12]."""
    return np.cumsum(np.asarray(weights)).tolist()


def partition_uniform(num_items, num_parts):
    """Evenly spaced boundaries; part p owns [bounds[p], bounds[p+1]).
    The trailing part absorbs the division remainder; with fewer items
    than parts, each item gets its own part and the rest sit empty."""
    if num_items <= num_parts:
        bounds = np.minimum(np.arange(num_parts + 1), num_items)
    else:
        bounds = np.arange(num_parts + 1) * (num_items // num_parts)
        bounds[num_parts] = num_items
    return bounds.tolist()


def _greedy_cuts(csum, num_parts, cap):
    """First-fit sweep over inclusive prefix sums `csum`: each cut is the
    furthest index that keeps the open part's weight within `cap`
    (np.searchsorted). Returns num_parts+1 boundaries; when the sweep
    finishes early the unused trailing parts sit empty at n."""
    n = len(csum)
    bounds = [0]
    base = 0.0
    for _ in range(num_parts - 1):
        cut = int(np.searchsorted(csum, base + cap, side="right"))
        # `base + cap` can round across an exact prefix-sum boundary;
        # settle the cut against the directly-computed part weight.
        while cut < n and float(csum[cut]) - base <= cap:
            cut += 1
        while cut - 1 > bounds[-1] and float(csum[cut - 1]) - base > cap:
            cut -= 1
        cut = min(max(cut, bounds[-1] + 1), n)  # always advance, never past n
        bounds.append(cut)
        base = float(csum[cut - 1])
    bounds.append(n)
    return bounds


def _fits(csum, num_parts, cap):
    """Does the first-fit sweep at `cap` leave every part — including the
    forced-advance and tail parts — no heavier than `cap`?"""
    bounds = _greedy_cuts(csum, num_parts, cap)
    prev = 0.0
    for b in bounds[1:]:
        here = float(csum[b - 1]) if b > 0 else 0.0
        if here - prev > cap:
            return False
        prev = here
    return True


def partition_balanced(weights, num_parts, eps=1e-3):
    """Contiguous split of `weights` into `num_parts` parts minimizing the
    heaviest part (reference contract, `utils.py:399`). Returns
    num_parts+1 boundary indices.

    Bisects on the bottleneck value between total/num_parts (perfect
    balance) and total (everything in one part), with the first-fit sweep
    as feasibility oracle, then cuts at the converged cap."""
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)
    csum = np.cumsum(np.asarray(weights, dtype=np.float64))
    total = float(csum[-1])
    lo, hi = total / num_parts, total
    while hi - lo > eps:
        mid = (lo + hi) / 2
        if _fits(csum, num_parts, mid):
            hi = mid
        else:
            lo = mid
    return _greedy_cuts(csum, num_parts, hi)


# ---------------------------------------------------------------------------
# PartitionedTensor — activation ("slice") parallelism container
# ---------------------------------------------------------------------------

class PartitionedTensor:
    """A flat 1/num_parts shard of a tensor plus meta to rebuild it.

    Host-side counterpart of the reference's `PartitionedTensor`
    (`utils.py:417`): the pipeline engine scatters inter-stage activations
    across the model-parallel group and reassembles on receive. Inside a
    jitted pipeline step the same job is done by sharding specs; this class
    serves the eager paths (checkpoint layout, meta handshakes, tests).
    """

    def __init__(self, tensor=None, num_parts=1, rank=0):
        self.num_parts = num_parts
        self.rank = rank
        if tensor is not None:
            self.orig_size = list(tensor.shape)
            self.local_data, self.partition = self._partition_tensor(
                jnp.asarray(tensor))

    @classmethod
    def from_meta(cls, meta, local_part, num_parts=None, rank=None):
        meta = [int(m) for m in np.asarray(meta)]
        obj = cls(tensor=None,
                  num_parts=num_parts if num_parts is not None else 0,
                  rank=rank if rank is not None else 0)
        ndims = meta[0]
        obj.orig_size = meta[1:1 + ndims]
        rest = meta[1 + ndims:]
        obj.num_parts = rest[0]
        obj.rank = rest[1]
        obj.partition = rest[2:]  # CSR-style rowptr, length num_parts+1
        obj.local_data = jnp.asarray(local_part)
        return obj

    def _partition_tensor(self, tensor):
        partition = partition_uniform(num_items=int(tensor.size),
                                      num_parts=self.num_parts)
        start = partition[self.rank]
        stop = partition[self.rank + 1]
        return tensor.reshape(-1)[start:stop], partition

    def full(self, gathered_parts=None):
        """Rebuild the full tensor. Single-host: supply every rank's shard
        via `gathered_parts`; defaults to zeros outside the local shard."""
        full_numel = int(np.prod(self.full_size()))
        flat = jnp.zeros([full_numel], dtype=self.local_data.dtype)
        if gathered_parts is None:
            gathered_parts = {self.rank: self.local_data}
        for part_id, data in gathered_parts.items():
            start = self.partition[part_id]
            stop = self.partition[part_id + 1]
            flat = flat.at[start:stop].set(jnp.asarray(data).reshape(-1))
        return flat.reshape(self.full_size())

    def to_meta(self):
        meta = [len(self.orig_size)]
        meta += list(self.orig_size)
        meta += [self.num_parts, self.rank]
        meta += list(self.partition)
        return np.asarray(meta, dtype=np.int64)

    def data(self):
        return self.local_data

    def local_size(self):
        return self.local_data.shape

    def full_size(self):
        return self.orig_size


# ---------------------------------------------------------------------------
# Memory reporting
# ---------------------------------------------------------------------------

def see_memory_usage(message, force=False):
    """Log device + host memory stats (reference `utils.py:569`)."""
    if not force:
        return
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        in_use = stats.get("bytes_in_use", 0) / 2 ** 30
        peak = stats.get("peak_bytes_in_use", 0) / 2 ** 30
        limit = stats.get("bytes_limit", 0) / 2 ** 30
        logger.info(f"{message} | HBM in-use {in_use:.2f} GB | "
                    f"peak {peak:.2f} GB | limit {limit:.2f} GB")
    except Exception:
        logger.info(f"{message} | device memory stats unavailable")
    try:
        import psutil
        vm = psutil.virtual_memory()
        logger.info(f"CPU virtual memory: used {vm.used / 2**30:.2f} GB, "
                    f"percent {vm.percent}%")
    except ImportError:
        pass


# ---------------------------------------------------------------------------
# Gradient noise scale (fork addition)
# ---------------------------------------------------------------------------

class GradientNoiseScale:
    """Estimate the gradient noise scale B_noise = tr(Σ)/|G|² from grads at
    two effective batch sizes (McCandlish et al. 2018), with EMA smoothing.
    `update(grads)` takes the current micro-batch grad pytree; every
    `n_batches` calls it compares the averaged grads against the freshest
    one. Fork addition: reference `utils.py:618-674`.
    """

    def __init__(self, batch_size_small, n_batches, beta=0.99, model=None):
        self.batch_size_small = batch_size_small
        self.batch_size_large = batch_size_small * n_batches
        self.n_batches = n_batches
        self.beta = beta
        self.model = model
        self.buffer = []
        self.ema_scale = None
        self.ema_noise = None
        self.scale = None
        self.noise = None
        self.noise_scale = None
        self.n_updates = 0
        self.skipped_nonfinite = 0

    def _ema(self, avg, value, i):
        avg = (avg or 0) * self.beta + (1 - self.beta) * value
        return avg, avg / (1 - self.beta ** (i + 1))

    @staticmethod
    def _flatten(grads):
        leaves = jax.tree_util.tree_leaves(grads)
        return jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1) for l in leaves])

    def _get_scale(self, g_small, g_big):
        return (g_small - g_big) / ((1 / self.batch_size_small) -
                                    (1 / self.batch_size_large))

    def _get_noise(self, g_small, g_big):
        return (self.batch_size_large * g_big -
                self.batch_size_small * g_small) / \
            (self.batch_size_large - self.batch_size_small)

    def update(self, grads):
        curr = self._flatten(grads)
        # host-side check (np, not a device reduction): the estimator's
        # consumers materialize `curr` on the host anyway
        if not np.isfinite(np.asarray(curr)).all():
            # One NaN/Inf micro-batch would poison the running sum AND
            # both EMAs permanently (every later estimate stays NaN).
            # Drop it: the step itself is handled by the loss-scaler /
            # sentinel skip machinery; the estimator just sees one fewer
            # sample.
            self.skipped_nonfinite += 1
            if self.skipped_nonfinite == 1:
                logger.warning(
                    "GradientNoiseScale: skipping a non-finite "
                    "micro-batch gradient (would permanently poison the "
                    "EMA estimates)")
            return
        # running sum, not a buffer of n_batches full gradient copies —
        # only the mean is ever consumed, and buffering costs
        # n_batches x model-size fp32 of live memory
        self.buffer = [curr] if not self.buffer else \
            [self.buffer[0] + curr]
        if self.n_updates % self.n_batches == self.n_batches - 1:
            past = self.buffer[0] / self.n_batches
            self.buffer = []
            g_big = float(jnp.mean(past ** 2))
            g_small = float(jnp.mean(curr ** 2))

            noise = self._get_noise(g_small, g_big)
            scale = self._get_scale(g_small, g_big)

            self.ema_scale, scale = self._ema(self.ema_scale, scale,
                                              self.n_updates)
            self.ema_noise, noise = self._ema(self.ema_noise, noise,
                                              self.n_updates)
            self.scale = float(scale)
            self.noise = float(noise)
            self.noise_scale = self.scale / self.noise if self.noise else None
        self.n_updates += 1

    def state_dict(self):
        """Accumulator state for full-state checkpoint resume. The
        running grad sum rides as float32 numpy, EMAs as Python floats —
        the round-trip is bit-exact."""
        return {
            "buffer": [np.asarray(b, np.float32) for b in self.buffer],
            "ema_scale": self.ema_scale,
            "ema_noise": self.ema_noise,
            "scale": self.scale,
            "noise": self.noise,
            "noise_scale": self.noise_scale,
            "n_updates": self.n_updates,
            "skipped_nonfinite": self.skipped_nonfinite,
        }

    def load_state_dict(self, sd):
        self.buffer = [jnp.asarray(b, jnp.float32) for b in sd["buffer"]]
        self.ema_scale = sd["ema_scale"]
        self.ema_noise = sd["ema_noise"]
        self.scale = sd["scale"]
        self.noise = sd["noise"]
        self.noise_scale = sd["noise_scale"]
        self.n_updates = int(sd["n_updates"])
        self.skipped_nonfinite = int(sd.get("skipped_nonfinite", 0))

    def reconcile_topology(self):
        """Elastic resume under a changed replica count: the mid-window
        micro-grad buffer was accumulated from the OLD sample stream —
        pairing it with post-restart micro-batches would compare grads
        that never co-occurred. Drop the partial window; the EMA
        estimates (per-replica batch sizes, topology-independent)
        survive."""
        mid_window = self.n_updates % self.n_batches
        if self.buffer or mid_window:
            logger.info(
                f"GradientNoiseScale: dropping a partial window "
                f"({mid_window} of {self.n_batches} micro-grads) after "
                "an elastic topology change; EMA estimates are kept")
        self.buffer = []
        # skip to the next window boundary so the next estimate averages
        # exactly n_batches post-restart micro-grads
        self.n_updates += (-self.n_updates) % self.n_batches
